"""Fault-domain tests: chaos sweep over every distributed protocol phase,
cross-host checkpoint resume via STORE_FETCH, circuit breaker open /
re-admission, fault-injection layer, FFT2 replay-cache bound.

The acceptance surface of the fleet fault domain (ISSUE 6): a worker
killed at ANY phase of a distributed prove — MSM, FFT_INIT, FFT1, the
EXCHANGE all-to-all, FFT2_PREPARE, FFT2 — still yields proof bytes
IDENTICAL to the host oracle's, and a worker restarted on a fresh host
resumes a prove from a store-fetched checkpoint without rebuilding keys.
"""

import os
import random
import subprocess
import sys
import time

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.runtime import protocol
from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
from distributed_plonk_tpu.runtime.health import LivenessTracker
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.service.metrics import Metrics

RNG = random.Random(0xFA17)
REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))

# Deflake discipline (ISSUE 12): these tests share the machine with the
# rest of tier-1 — worker subprocess startup and 5 s HEALTH probes that
# are instant in isolation can blow fixed windows under load. EVERY wait
# in this module is event-driven against a generous deadline (the happy
# path still exits in milliseconds), never a fixed sleep or a one-shot
# probe.
_LOAD_BUDGET_S = float(os.environ.get("DPT_TEST_WAIT_S", "120"))


def _wait_for(cond, timeout_s=None, interval=0.05, msg=""):
    """Poll `cond` until truthy; returns its value. AssertionError with
    `msg` on deadline — the event-driven replacement for fixed sleeps."""
    deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
    while True:
        got = cond()
        if got:
            return got
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {msg or cond}")
        time.sleep(interval)


def _probe_until(handle, timeout_s=None, probe_ms=5000):
    """Fresh-connection HEALTH snapshot, retried: one probe can time out
    under tier-1 load without the worker being down."""
    return _wait_for(lambda: handle.probe(timeout_ms=probe_ms),
                     timeout_s=timeout_s, interval=0.2,
                     msg=f"probe of {handle.host}:{handle.port}")


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    """Tight backoff so recovery paths run in test time, not wall-clock
    minutes (the knobs are class attributes latched from env at import)."""
    monkeypatch.setattr(WorkerHandle, "RECONNECT_TRIES", 2)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_BASE_S", 0.01)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_MAX_S", 0.05)
    monkeypatch.setattr(WorkerHandle, "TIMEOUT_MS", 120000)


class Fleet:
    """N worker processes whose members can be killed and restarted by
    index — the process-level chaos plane the FaultInjector's kill_cb
    plugs into."""

    def __init__(self, tmp_path, n, port_base, backend="python"):
        self.n = n
        self.backend = backend
        base = port_base + (os.getpid() % 400) * (n + 1)
        self.cfg = NetworkConfig(
            [f"127.0.0.1:{base + i}" for i in range(n)])
        self.cfg_path = str(tmp_path / "network.json")
        self.cfg.save(self.cfg_path)
        self.procs = [None] * n
        for i in range(n):
            self.start(i)

    def start(self, i):
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
             str(i), self.cfg_path, "--backend", self.backend], cwd=REPO)

    def kill(self, i):
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait(timeout=10)

    def restart(self, i):
        self.kill(i)
        self.start(i)

    def wait_up(self, timeout_s=None):
        """Block until every worker answers a fresh-connection probe.
        Budget covers loaded-machine subprocess startup (interpreter +
        imports can take tens of seconds when tier-1 owns the cores)."""
        deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
        pending = set(range(self.n))
        while pending and time.monotonic() < deadline:
            for i in sorted(pending):
                h, p = self.cfg.workers[i]
                if WorkerHandle(h, p).probe(timeout_ms=5000) is not None:
                    pending.discard(i)
            if pending:
                time.sleep(0.2)
        assert not pending, f"workers {sorted(pending)} did not come up"

    def close(self):
        for i in range(self.n):
            if self.procs[i] is not None and self.procs[i].poll() is None:
                self.procs[i].kill()
        for p in self.procs:
            if p is not None:
                p.wait(timeout=10)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    f = Fleet(tmp_path_factory.mktemp("faults"), 3, 29000)
    try:
        f.wait_up()
        yield f
    finally:
        f.close()


def _dispatcher(fleet, metrics=None, faults=None, breaker_k=2):
    d = Dispatcher(fleet.cfg, metrics=metrics, faults=faults)
    # fast breaker/probe windows; re-point the handles at the new tracker
    d.tracker = LivenessTracker(fleet.n, breaker_k=breaker_k,
                                probe_base_s=0.05, probe_max_s=0.5,
                                metrics=d.metrics)
    for w in d.workers:
        w.tracker = d.tracker
    return d


def _close(d):
    """Drop dispatcher connections WITHOUT shutting the shared fleet down."""
    for w in d.workers:
        w.close()
    d.pool.shutdown(wait=False)


# --- the chaos sweep ---------------------------------------------------------

# (label, tag the rule matches on, rule-target worker, process to kill):
# killing worker 1 while the dispatcher talks to worker 0 at FFT2_PREPARE
# is the EXCHANGE case — the death is only observable through the peer
# all-to-all plane, and failure attribution needs the fleet probe
_SWEEP = [
    ("msm", protocol.MSM, 1, 1),
    ("fft_init", protocol.FFT_INIT, 1, 1),
    ("fft1", protocol.FFT1, 1, 1),
    ("exchange", protocol.FFT2_PREPARE, 0, 1),
    ("fft2_prepare", protocol.FFT2_PREPARE, 1, 1),
    ("fft2", protocol.FFT2, 1, 1),
]


@pytest.mark.parametrize("label,tag,rule_worker,victim",
                         _SWEEP, ids=[s[0] for s in _SWEEP])
def test_chaos_sweep_byte_identical_proof(fleet, proven, label, tag,
                                          rule_worker, victim):
    """Kill a worker at one exact protocol phase of a fully distributed
    prove (sharded 4-step FFTs + distributed MSM): the fleet recovers —
    range adoption for MSM, probe + replan (or quorum degradation) for the
    FFT — and the proof bytes match the host oracle exactly."""
    ckt, pk, vk, proof_host = proven
    fleet.restart(victim)  # clean slate from any earlier phase
    fleet.wait_up()
    metrics = Metrics()
    faults = FaultInjector(
        [Rule("kill", tag=tag, worker=rule_worker, nth=1)],
        kill_cb=lambda _w: fleet.kill(victim), metrics=metrics)
    d = _dispatcher(fleet, metrics=metrics, faults=faults)
    try:
        proof = prove_remote(ckt, pk, d)
        assert proof.opening_proof == proof_host.opening_proof, label
        assert proof.shifted_opening_proof == proof_host.shifted_opening_proof
        assert proof.wires_poly_comms == proof_host.wires_poly_comms
        assert proof.split_quot_poly_comms == proof_host.split_quot_poly_comms
        snap = metrics.snapshot()["counters"]
        assert snap.get("faults_injected_kill", 0) == 1, label
        # at least one recovery event must have fired somewhere
        recoveries = sum(snap.get(k, 0) for k in (
            "fleet_range_adoptions", "fleet_fft_replans",
            "fleet_fft_degraded", "fleet_reconnects"))
        assert recoveries >= 1, (label, snap)
    finally:
        _close(d)
    fleet.restart(victim)
    fleet.wait_up()


def prove_remote(ckt, pk, d):
    from distributed_plonk_tpu.prover import prove
    return prove(random.Random(1), ckt, pk,
                 RemoteBackend(d, dist_fft_min=ckt.n))


def test_fft_quorum_degradation(fleet, proven):
    """With every worker but one dead, fft_dist degrades to the
    single-worker NTT path and still returns oracle bytes."""
    from distributed_plonk_tpu import poly as P
    fleet.wait_up()
    metrics = Metrics()
    d = _dispatcher(fleet, metrics=metrics, breaker_k=1)
    try:
        n = 64
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        fleet.kill(1)
        fleet.kill(2)
        got = d.fft_dist(values, inverse=True)
        assert got == P.ifft(P.Domain(n), values)
        snap = metrics.snapshot()["counters"]
        assert snap.get("fleet_fft_degraded", 0) >= 1
    finally:
        _close(d)
    fleet.restart(1)
    fleet.restart(2)
    fleet.wait_up()


# --- circuit breaker + re-admission ------------------------------------------

def test_breaker_open_adoption_and_readmission(fleet):
    fleet.wait_up()
    metrics = Metrics()
    d = _dispatcher(fleet, metrics=metrics, breaker_k=1)
    try:
        n = 48
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        want = C.g1_msm(bases, scalars)
        d.init_bases(bases)
        assert d.msm(scalars) == want

        fleet.kill(2)
        assert d.msm(scalars) == want          # range 2 adopted
        assert d._adopted.get(2) is not None
        assert not d.tracker.usable(2)         # breaker open
        snap = metrics.snapshot()["counters"]
        assert snap.get("fleet_breaker_opens", 0) >= 1
        assert snap.get("fleet_range_adoptions", 0) >= 1

        # breaker-open worker fast-fails without dialing
        from distributed_plonk_tpu.runtime.dispatcher import WorkerUnavailable
        with pytest.raises(WorkerUnavailable):
            d.workers[2].call(protocol.PING)

        # worker returns on the same port: next due probe re-admits it and
        # re-provisions its own range (the adoption redirect is dropped).
        # Event-driven: one half-open probe can time out under load (the
        # 5 s budget is not a liveness verdict on a loaded box), so keep
        # forcing the window until the re-admission actually lands — the
        # MSM result must be correct on EVERY iteration either way.
        fleet.restart(2)
        fleet.wait_up()

        def _readmitted():
            d.tracker.force_probe(2)
            assert d.msm(scalars) == want
            return d.tracker.usable(2) and 2 not in d._adopted
        _wait_for(_readmitted, msg="worker 2 re-admission")
        snap = metrics.snapshot()["counters"]
        assert snap.get("fleet_readmissions", 0) >= 1
        # and the re-admitted worker actually serves again
        assert d.msm(scalars) == want
        stats = _probe_until(d.workers[2])
        assert stats["served"] >= 1
    finally:
        _close(d)


def test_drop_and_corrupt_frames_recovered(fleet):
    """A dropped frame is resent over a fresh stream (idempotent worker
    handlers); a tag-corrupted frame draws a loud ERR and the recovery
    path recomputes — results stay exact in both cases."""
    from distributed_plonk_tpu import poly as P
    fleet.wait_up()
    metrics = Metrics()
    faults = FaultInjector(
        [Rule("drop", tag=protocol.NTT, nth=1),
         Rule("corrupt", tag=protocol.MSM, nth=1)], metrics=metrics)
    d = _dispatcher(fleet, metrics=metrics, faults=faults)
    try:
        n = 32
        domain = P.Domain(n)
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        assert d.ntt(values) == P.fft(domain, values)

        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        d.init_bases(bases)
        assert d.msm(scalars) == C.g1_msm(bases, scalars)

        snap = metrics.snapshot()["counters"]
        assert snap.get("faults_injected_drop", 0) == 1
        assert snap.get("faults_injected_corrupt", 0) == 1
        assert snap.get("fleet_reconnects", 0) >= 1
    finally:
        _close(d)


def test_failed_base_push_never_serves_stale_bases(fleet):
    """Regression (the intermittent wrong-proof behind the fleet-TCP
    flakes): when one worker's INIT_BASES push fails during a
    re-provisioning, that worker still holds the PREVIOUS provisioning's
    set under the same id — an MSM routed to it would succeed with the
    wrong bases. The dispatcher must remember the failed push and route
    that range through the adoption path (fresh bases re-pushed), never
    trust the stale owner."""
    fleet.wait_up()
    metrics = Metrics()
    # worker 2's SECOND INIT_BASES frame draws an ERR (tag corrupted):
    # the first provisioning lands everywhere, the second one fails for
    # worker 2 only — leaving its set-2 bases stale
    faults = FaultInjector(
        [Rule("corrupt", tag=protocol.INIT_BASES, worker=2, nth=2)],
        metrics=metrics)
    d = _dispatcher(fleet, metrics=metrics, faults=faults)
    try:
        n = 30
        bases1 = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                  for _ in range(n)]
        scalars1 = [RNG.randrange(R_MOD) for _ in range(n)]
        d.init_bases(bases1)
        assert d.msm(scalars1) == C.g1_msm(bases1, scalars1)
        assert d._unprovisioned == set()

        bases2 = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                  for _ in range(n)]
        scalars2 = [RNG.randrange(R_MOD) for _ in range(n)]
        d.init_bases(bases2)
        assert d._unprovisioned == {2}
        # stale-owner routing would return a WRONG point here; the
        # adoption path re-pushes range 2's new bases and stays exact
        assert d.msm(scalars2) == C.g1_msm(bases2, scalars2)
        snap = metrics.snapshot()["counters"]
        assert snap.get("faults_injected_corrupt", 0) == 1
        assert snap.get("fleet_range_adoptions", 0) >= 1
        assert 2 not in d._unprovisioned
        # later msms keep routing through the adopter, still exact
        assert d.msm(scalars2) == C.g1_msm(bases2, scalars2)
    finally:
        _close(d)


def test_liveness_tracker_unit():
    t = LivenessTracker(2, breaker_k=3, probe_base_s=0.01, probe_max_s=0.05)
    assert t.usable(0)
    t.record_failure(0)
    t.record_failure(0)
    assert t.usable(0)            # 2 < K
    assert t.record_failure(0)    # K-th opens
    assert not t.usable(0)
    assert not t.probe_due(0)     # backoff window not yet elapsed
    time.sleep(0.06)
    assert t.probe_due(0)
    assert not t.probe_due(0)     # half-open: one owner per window
    assert t.record_ok(0)         # probe success re-admits
    assert t.usable(0)
    # success resets the consecutive count
    t.record_failure(0)
    t.record_failure(0)
    t.record_ok(0)
    t.record_failure(0)
    t.record_failure(0)
    assert t.usable(0)
    # an unrelated worker is untouched throughout
    assert t.usable(1)


# --- store-backed checkpoints + cross-host resume ----------------------------

def test_cross_host_resume_via_store_fetch(tmp_path, proven):
    """Host A dies mid-prove with its checkpoint in the artifact store; a
    'replacement host' (fresh store) STORE_FETCHes the snapshot + bucket
    keys over the wire and finishes the prove — byte-identical to an
    uninterrupted run, with zero key building on the new host."""
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.checkpoint import StoreCheckpoint
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.service import ProofService
    from distributed_plonk_tpu.store import ArtifactStore, fetch_into

    ckt, pk, vk, proof_host = proven
    store_a = ArtifactStore(str(tmp_path / "host_a"))

    class _DieAfterRound2(StoreCheckpoint):
        def save(self, round_no, *a, **kw):
            super().save(round_no, *a, **kw)
            if round_no == 2:
                raise RuntimeError("host A lost power")

    with pytest.raises(RuntimeError, match="lost power"):
        prove(random.Random(1), ckt, pk, PythonBackend(),
              checkpoint=_DieAfterRound2(store_a, "job-xh"))
    assert "ckpt:job-xh" in store_a.keys()

    # host A's store is served over the wire by its (restarted) service
    svc = ProofService(port=0, store_dir=str(tmp_path / "host_a")).start()
    try:
        store_b = ArtifactStore(str(tmp_path / "host_b"))
        blob = fetch_into(store_b, "127.0.0.1", svc.port, "ckpt:job-xh")
        assert blob is not None
        assert "ckpt:job-xh" in store_b.keys()
        # a missing key is a clean miss, not an exception
        assert fetch_into(store_b, "127.0.0.1", svc.port, "nope") is None
    finally:
        svc.shutdown()

    # replacement host resumes at round 3 and matches the golden bytes
    resumed = StoreCheckpoint(store_b, "job-xh")
    assert resumed.load(_fingerprint(pk, ckt))["round"] == 2
    proof = prove(random.Random(1), ckt, pk, PythonBackend(),
                  checkpoint=resumed)
    assert proof.opening_proof == proof_host.opening_proof
    assert proof.wires_evals == proof_host.wires_evals
    assert resumed.load(_fingerprint(pk, ckt)) is None  # cleared on success


def _fingerprint(pk, ckt):
    from distributed_plonk_tpu.checkpoint import workload_fingerprint
    return workload_fingerprint(pk.vk, ckt.public_input())


def test_bucket_keys_from_peer_no_rebuild(tmp_path, monkeypatch):
    """A fresh service with an empty store and a warm peer serves a seen
    shape WITHOUT building keys: the bucket blob arrives via STORE_FETCH
    (key build forbidden by monkeypatch on the new host)."""
    import json
    from distributed_plonk_tpu.service import (ProofService, ServiceClient)
    from distributed_plonk_tpu.service import jobs as J

    spec = {"kind": "toy", "gates": 16, "seed": 5}
    svc_a = ProofService(port=0, prover_workers=1,
                         store_dir=str(tmp_path / "a")).start()
    try:
        with ServiceClient("127.0.0.1", svc_a.port) as c:
            jid = c.submit(spec)["job_id"]
            st = c.wait(jid, timeout_s=120)
            assert st["state"] == "done"

        # host B: empty store, peer = host A. Building keys is forbidden.
        def _forbidden(*a, **kw):
            raise AssertionError("key build on the warm-peer path")
        monkeypatch.setattr(J, "build_bucket_keys", _forbidden)
        svc_b = ProofService(port=0, prover_workers=1,
                             store_dir=str(tmp_path / "b"),
                             store_peers=[("127.0.0.1", svc_a.port)]).start()
        try:
            with ServiceClient("127.0.0.1", svc_b.port) as c:
                jid = c.submit(dict(spec, seed=6))["job_id"]
                st = c.wait(jid, timeout_s=120)
                assert st["state"] == "done", json.dumps(st)
                m = c.metrics()
            assert m["counters"].get("bucket_peer_hits", 0) == 1
            assert m["counters"].get("bucket_misses", 0) == 0
        finally:
            svc_b.shutdown()
    finally:
        svc_a.shutdown()


def test_corrupt_checkpoint_detected_then_clean_restart(tmp_path):
    """corrupt_ckpt injection flips a byte under the just-saved snapshot;
    a kill at the same round forces a resume attempt. The store's SHA-256
    rejects the snapshot, the retry restarts from round 1 (not garbage),
    and the proof still verifies."""
    import json
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service.jobs import build_bucket_keys, JobSpec
    from distributed_plonk_tpu.proof_io import deserialize_proof
    from distributed_plonk_tpu.verifier import verify

    faults = FaultInjector([Rule("corrupt_ckpt", tag=2, nth=1)])
    svc = ProofService(port=0, prover_workers=1, chaos=True,
                       store_dir=str(tmp_path / "s"), faults=faults).start()
    try:
        with ServiceClient("127.0.0.1", svc.port) as c:
            jid = c.submit({"kind": "toy", "gates": 60, "seed": 9})["job_id"]
            deadline = time.monotonic() + 60
            killed = False
            while time.monotonic() < deadline and not killed:
                st = c.status(jid)
                if st["state"] in ("done", "failed"):
                    break
                if st["state"] == "running":
                    try:
                        c.kill_worker(job_id=jid, at_round=2)
                        killed = True
                    except Exception:
                        break
                time.sleep(0.005)
            st = c.wait(jid, timeout_s=120)
            assert st["state"] == "done", json.dumps(st)
            header, blob = c.result(jid)
            m = c.metrics()
        spec = JobSpec.from_wire(header["spec"])
        vk = build_bucket_keys(spec)[2]
        pub = [int(x, 16) for x in header["public_input"]]
        assert verify(vk, pub, deserialize_proof(blob),
                      rng=random.Random(1))
        if killed and st["retries"]:
            # the retry hit the corrupted snapshot: detected, not resumed
            assert m["counters"].get("faults_ckpt_corrupted", 0) >= 1
            assert m["counters"].get("checkpoint_resumes", 0) == 0
    finally:
        svc.shutdown()


# --- FFT2 replay cache bound -------------------------------------------------

def test_fft_task_cache_capped():
    from distributed_plonk_tpu.runtime.worker import _evict_fft_tasks

    class T:
        def __init__(self, created, done_at=None):
            self.created = created
            self.done_at = done_at

    now = 1000.0
    tasks = {}
    # 40 completed (oldest done first) + 40 in-flight
    for i in range(40):
        tasks[i] = T(created=now - 100 + i, done_at=now - 50 + i)
    for i in range(40, 80):
        tasks[i] = T(created=now - 100 + i)
    _evict_fft_tasks(tasks, cap=64, now=now)
    assert len(tasks) == 63  # room for the incoming task
    # completed tasks evicted FIRST, oldest-done first
    done_left = [tid for tid, t in tasks.items() if t.done_at is not None]
    assert done_left == list(range(17, 40))
    # all in-flight survive while completed ones can cover the excess
    assert all(tid in tasks for tid in range(40, 80))
    # when completed can't cover it, oldest in-flight go next
    _evict_fft_tasks(tasks, cap=10, now=now)
    assert len(tasks) == 9
    assert all(t.done_at is None for t in tasks.values())
    assert sorted(tasks) == list(range(71, 80))
    # TTL purge still applies (done TTL is the short one)
    _evict_fft_tasks(tasks, cap=64, now=now + 10000)
    assert not tasks


def test_fft_task_cap_live(fleet):
    """A live worker holds at most DPT_FFT_TASK_CAP resident tasks no
    matter how many FFT_INITs land (HEALTH exposes the table size)."""
    fleet.wait_up()
    d = _dispatcher(fleet)
    try:
        col_ranges = [(0, 1), (1, 2), (2, 4)]
        for t in range(70):
            d.workers[0].call(
                protocol.FFT_INIT,
                protocol.encode_fft_init(10_000 + t, False, False,
                                         16, 4, 4, 0, 2, col_ranges))
        # retried probe: a single 5 s HEALTH round trip can time out
        # under tier-1 load without the worker being down
        snap = _probe_until(d.workers[0])
        assert snap["fft_tasks"] <= 64
    finally:
        _close(d)
