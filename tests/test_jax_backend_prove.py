"""End-to-end prove on the JAX device backend.

The device analog of the reference's `test2` (fully-distributed prove,
/root/reference/src/dispatcher2.rs:1273-1295): every FFT and MSM of the
5-round prover runs through the device kernels, the proof must be
bit-identical to the host-oracle proof (same rng) and verify.
"""

import random

import pytest

from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.verifier import verify
from distributed_plonk_tpu.backend.jax_backend import JaxBackend


def test_jax_prove_verifies_and_matches_oracle(proven):
    ckt, pk, vk, proof_host = proven
    be = JaxBackend()
    proof_dev = prove(random.Random(1), ckt, pk, be)
    assert verify(vk, ckt.public_input(), proof_dev, rng=random.Random(2))

    # device residency: O(n) host->device uploads are the proving key, the
    # circuit witness/permutation tables (once each, cached) and the
    # public-input vector; the only lower is the single batched round-4
    # evaluation transfer (everything else stays on device between rounds)
    assert be.lifts == 3, be.lifts
    assert be.lowers == 1, be.lowers

    # bit-identical across backends (the reference's core invariant:
    # distributed == single-node, SURVEY.md §4)
    assert proof_dev.wires_poly_comms == proof_host.wires_poly_comms
    assert proof_dev.prod_perm_poly_comm == proof_host.prod_perm_poly_comm
    assert proof_dev.split_quot_poly_comms == proof_host.split_quot_poly_comms
    assert proof_dev.opening_proof == proof_host.opening_proof
    assert proof_dev.shifted_opening_proof == proof_host.shifted_opening_proof
    assert proof_dev.wires_evals == proof_host.wires_evals
    assert proof_dev.wire_sigma_evals == proof_host.wire_sigma_evals
    assert proof_dev.perm_next_eval == proof_host.perm_next_eval


@pytest.mark.slow
def test_jax_prove_msm_pallas_byte_identical(proven, monkeypatch):
    """DPT_MSM_KERNEL=pallas (the fused VMEM-resident bucket kernel)
    produces the SAME proof bytes as the host oracle — and therefore as
    the default-kernel prove above. Slow tier: every commitment batch
    recompiles through the interpret-mode Mosaic emulation."""
    from distributed_plonk_tpu import proof_io
    from distributed_plonk_tpu.backend import msm_jax

    ckt, pk, vk, proof_host = proven
    monkeypatch.setattr(msm_jax, "_MSM_KERNEL", "pallas")
    proof_pl = prove(random.Random(1), ckt, pk, JaxBackend())
    assert (proof_io.serialize_proof(proof_pl)
            == proof_io.serialize_proof(proof_host))


@pytest.mark.slow
def test_jax_prove_ntt_pallas_byte_identical(proven, monkeypatch):
    """DPT_NTT_KERNEL=pallas (the fused multi-stage VMEM-resident NTT)
    produces the SAME proof bytes as the host oracle — every forward /
    inverse / coset NTT of all 5 rounds goes through the fused groups.
    Slow tier: each distinct (mode, domain) NTT program recompiles
    through the interpret-mode emulation."""
    from distributed_plonk_tpu import proof_io
    from distributed_plonk_tpu.backend import ntt_jax

    ckt, pk, vk, proof_host = proven
    monkeypatch.setattr(ntt_jax, "_NTT_KERNEL", "pallas")
    proof_pl = prove(random.Random(1), ckt, pk, JaxBackend())
    assert (proof_io.serialize_proof(proof_pl)
            == proof_io.serialize_proof(proof_host))


@pytest.mark.slow
def test_jax_prove_r3_unfused_byte_identical(proven, monkeypatch):
    """DPT_R3_FUSE=0 (the standalone gate/sigma/combine step programs)
    produces the SAME proof bytes as the default fused round 3 — the
    tier-1 oracle test above runs the FUSED path, so together they pin
    both sides of the round-3 fusion seam."""
    from distributed_plonk_tpu import proof_io
    from distributed_plonk_tpu.backend import jax_backend

    ckt, pk, vk, proof_host = proven
    monkeypatch.setattr(jax_backend, "_R3_FUSE", False)
    proof_uf = prove(random.Random(1), ckt, pk, JaxBackend())
    assert (proof_io.serialize_proof(proof_uf)
            == proof_io.serialize_proof(proof_host))


@pytest.mark.slow
def test_jax_prove_radix2_byte_identical(proven, monkeypatch):
    """DPT_NTT_RADIX=2 (the parity/debug core) produces the SAME proof
    bytes as the host oracle — and therefore as the default radix-4
    prove above. Slow tier: a second full set of prover-kernel compiles."""
    from distributed_plonk_tpu import proof_io

    ckt, pk, vk, proof_host = proven
    monkeypatch.setenv("DPT_NTT_RADIX", "2")
    proof_r2 = prove(random.Random(1), ckt, pk, JaxBackend())
    assert (proof_io.serialize_proof(proof_r2)
            == proof_io.serialize_proof(proof_host))
