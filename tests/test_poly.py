"""Reference NTT tests, incl. the 4-step r x c decomposition spec.

The 4-step checks mirror the reference's own spec test
(/root/reference/src/playground.rs:82-103): a size-N FFT computed as
column FFTs + twiddle + row FFTs over an r x c matrix must equal the
direct FFT for all of {fwd, inv} x {plain, coset}.
"""

import random

from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD, FR_GENERATOR
from distributed_plonk_tpu.fields import fr_inv

rng = random.Random(0x4477)


def naive_dft(domain, coeffs):
    n = domain.size
    w = domain.group_gen
    out = []
    for i in range(n):
        acc = 0
        for j, c in enumerate(coeffs):
            acc = (acc + c * pow(w, i * j, R_MOD)) % R_MOD
        out.append(acc)
    return out


def test_fft_matches_naive():
    d = P.Domain(16)
    coeffs = [rng.randrange(R_MOD) for _ in range(16)]
    assert P.fft(d, coeffs) == naive_dft(d, coeffs)


def test_fft_ifft_roundtrip():
    d = P.Domain(64)
    coeffs = [rng.randrange(R_MOD) for _ in range(64)]
    assert P.ifft(d, P.fft(d, coeffs)) == coeffs
    assert P.coset_ifft(d, P.coset_fft(d, coeffs)) == coeffs


def test_coset_fft_is_shifted_eval():
    d = P.Domain(8)
    coeffs = [rng.randrange(R_MOD) for _ in range(8)]
    evals = P.coset_fft(d, coeffs)
    g = FR_GENERATOR
    for i, e in enumerate(evals):
        x = g * pow(d.group_gen, i, R_MOD) % R_MOD
        assert e == P.poly_eval(coeffs, x)


def _transpose(m):
    return [list(row) for row in zip(*m)]


def four_step_fft(domain, coeffs, is_inv, is_coset):
    """The r x c decomposition the distributed NTT implements.

    Stage 1 (per matrix row i of the transposed layout): optional coset
    pre-scale by g^(i + j*r), c-point (i)FFT, twiddle by w^(+-i*j).
    Stage 2 (per column): r-point (i)FFT, optional inverse-coset post-scale
    by g^-(i + j*c). Matches /root/reference/src/worker.rs:66-115.
    """
    n = domain.size
    r = 1 << (domain.log_size >> 1)
    c = n // r
    c_dom = P.Domain(c)
    r_dom = P.Domain(r)
    g = FR_GENERATOR
    g_inv = fr_inv(g)
    omega = domain.group_gen_inv if is_inv else domain.group_gen

    v = list(coeffs) + [0] * (n - len(coeffs))
    # view as c-major: t[i][j] = v[j*r + i], i in [0,r), j in [0,c)
    mat = _transpose([v[k * r:(k + 1) * r] for k in range(c)])
    # stage 1: row i holds c entries
    for i in range(r):
        row = mat[i]
        if is_coset and not is_inv:
            row = [u * pow(g, i + j * r, R_MOD) % R_MOD for j, u in enumerate(row)]
        row = P.ifft(c_dom, row) if is_inv else P.fft(c_dom, row)
        row = [u * pow(omega, i * j, R_MOD) % R_MOD for j, u in enumerate(row)]
        mat[i] = row
    # all-to-all transpose
    cols = _transpose(mat)
    # stage 2: column j holds r entries
    for i in range(c):
        col = cols[i]
        col = P.ifft(r_dom, col) if is_inv else P.fft(r_dom, col)
        if is_coset and is_inv:
            col = [u * pow(g_inv, i + j * c, R_MOD) % R_MOD for j, u in enumerate(col)]
        cols[i] = col
    return [x for row in _transpose(cols) for x in row]


def test_four_step_equals_direct_all_modes():
    for n in (64, 128):
        d = P.Domain(n)
        coeffs = [rng.randrange(R_MOD) for _ in range(n)]
        for is_inv in (False, True):
            for is_coset in (False, True):
                if is_coset and not is_inv:
                    expect = P.coset_fft(d, coeffs)
                elif is_coset and is_inv:
                    expect = P.coset_ifft(d, coeffs)
                elif is_inv:
                    expect = P.ifft(d, coeffs)
                else:
                    expect = P.fft(d, coeffs)
                got = four_step_fft(d, coeffs, is_inv, is_coset)
                assert got == expect, (n, is_inv, is_coset)


def test_synthetic_division():
    coeffs = [rng.randrange(R_MOD) for _ in range(33)]
    z = rng.randrange(R_MOD)
    q = P.synthetic_divide(coeffs, z)
    # p(X) - p(z) == q(X) * (X - z)
    pz = P.poly_eval(coeffs, z)
    # direct check: evaluate both sides at random points
    for _ in range(5):
        x = rng.randrange(R_MOD)
        lhs = (P.poly_eval(coeffs, x) - pz) % R_MOD
        rhs = P.poly_eval(q, x) * ((x - z) % R_MOD) % R_MOD
        assert lhs == rhs


def test_poly_mul_vanishing():
    a = [rng.randrange(R_MOD) for _ in range(5)]
    out = P.poly_mul_vanishing(a, 8)
    x = rng.randrange(R_MOD)
    assert P.poly_eval(out, x) == P.poly_eval(a, x) * ((pow(x, 8, R_MOD) - 1) % R_MOD) % R_MOD
