"""Device G1 kernels + MSM vs the curve.py oracle."""

import random

import jax
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import curve_jax as CJ
from distributed_plonk_tpu.backend import msm_jax

RNG = random.Random(0xC0FFEE)


def _rand_points(n):
    return [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n)]


def test_jac_add_double_random():
    n = 16
    ps = _rand_points(n)
    qs = _rand_points(n)
    dev_p = CJ.affine_to_device(ps)
    dev_q = CJ.affine_to_device(qs)
    add_fn = jax.jit(CJ.jac_add)
    dbl_fn = jax.jit(CJ.jac_double)
    got_add = CJ.device_to_affine(add_fn(dev_p, dev_q))
    got_dbl = CJ.device_to_affine(dbl_fn(dev_p))
    assert got_add == [C.g1_add_affine(p, q) for p, q in zip(ps, qs)]
    assert got_dbl == [C.g1_add_affine(p, p) for p in ps]


def test_jac_add_edge_cases():
    p = _rand_points(1)[0]
    q = _rand_points(1)[0]
    lhs = [p, p, p, None, None, p]
    rhs = [p, C.g1_neg(p), None, p, None, q]
    dev_l = CJ.affine_to_device(lhs)
    dev_r = CJ.affine_to_device(rhs)
    got = CJ.device_to_affine(jax.jit(CJ.jac_add)(dev_l, dev_r))
    assert got == [C.g1_add_affine(a, b) for a, b in zip(lhs, rhs)]


@pytest.mark.parametrize("n", [64])
def test_msm_matches_oracle(n):
    bases = _rand_points(n - 2) + [None, None]  # infinity padding like the SRS
    scalars = ([RNG.randrange(R_MOD) for _ in range(n - 4)]
               + [0, 1, R_MOD - 1, RNG.randrange(R_MOD)])
    got = msm_jax.msm(bases, scalars)
    assert got == C.g1_msm(bases, scalars)


def test_msm_short_scalars_and_reuse():
    bases = _rand_points(32)
    ctx = msm_jax.MsmContext(bases)
    s1 = [RNG.randrange(R_MOD) for _ in range(20)]  # shorter than bases
    s2 = [RNG.randrange(R_MOD) for _ in range(32)]
    assert ctx.msm(s1) == C.g1_msm(bases[:20], s1)
    assert ctx.msm(s2) == C.g1_msm(bases, s2)


def test_msm_aot_compile_then_correct():
    """warm_stages' true AOT path: lower().compile() every pipeline stage
    without executing anything — digit extraction at the COMMIT-handle
    widths (it jit-caches per exact width; warm_stages passes n+2/n+3),
    then verify a real Montgomery-handle commit and a scalar MSM still
    match the oracle."""
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs
    from distributed_plonk_tpu.constants import FR_MONT_R

    bases = _rand_points(32)
    ctx = msm_jax.MsmContext(bases)
    report = ctx.aot_compile(batch_sizes=(1, 2), digit_widths=(20, 32))
    # 2x digit extraction + 2x (chunk scan, finish, merge)
    assert report["compiled"] == 8 and report["failed"] == 0, report
    assert [s["batch"] for s in report["shapes"]] == [1, 2]
    scalars = [RNG.randrange(R_MOD) for _ in range(32)]
    assert ctx.msm(scalars) == C.g1_msm(bases, scalars)
    h = jnp.asarray(ints_to_limbs(
        [s * FR_MONT_R % R_MOD for s in scalars[:20]], 16))  # warmed width
    assert ctx.msm_mont_limbs(h) == C.g1_msm(bases[:20], scalars[:20])


def _proj_to_affine_list(p3):
    """Per-column decode via the production converter (no re-implementation
    of the Montgomery/Z-inversion logic)."""
    import numpy as np

    tx, ty, tz = (np.asarray(c) for c in p3)
    return [msm_jax._proj_limbs_to_affine(tx[:, j], ty[:, j], tz[:, j])
            for j in range(tx.shape[1])]


def _affine_to_proj(points):
    """list[(x, y) | None] -> projective device tuple ((24, n),)*3 with
    identity = (0 : 1 : 0)."""
    import jax.numpy as jnp
    from distributed_plonk_tpu.constants import Q_MOD, FQ_MONT_R
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs

    xs = [(p[0] * FQ_MONT_R % Q_MOD) if p else 0 for p in points]
    ys = [(p[1] * FQ_MONT_R % Q_MOD) if p else FQ_MONT_R % Q_MOD
          for p in points]
    zs = [FQ_MONT_R % Q_MOD if p else 0 for p in points]
    return tuple(jnp.asarray(ints_to_limbs(v, 24)) for v in (xs, ys, zs))


def test_proj_complete_add_matches_oracle():
    """RCB15 complete adds (the signed bucket pipeline's group ops) vs the
    oracle, covering the cases a complete formula must absorb with no
    special handling: P+Q, P+P, P+(-P), identity on either/both sides."""
    import jax.numpy as jnp

    p = _rand_points(1)[0]
    q = _rand_points(1)[0]
    lhs = [p, p, p, None, None, p, q]
    rhs = [p, C.g1_neg(p), None, p, None, q, p]
    want = [C.g1_add_affine(a, b) for a, b in zip(lhs, rhs)]

    got = _proj_to_affine_list(jax.jit(CJ.proj_add)(
        _affine_to_proj(lhs), _affine_to_proj(rhs)))
    assert got == want

    # mixed variant: q affine + inf mask (q = None lanes masked)
    x, y, inf = msm_jax.points_to_device(rhs, 0)
    got_m = _proj_to_affine_list(jax.jit(CJ.proj_add_mixed)(
        _affine_to_proj(lhs), (jnp.asarray(x), jnp.asarray(y)),
        jnp.asarray(inf)))
    assert got_m == want


def test_batch_to_affine_roundtrip():
    """Jacobian points with arbitrary Z (like a fixed-base SRS) normalize
    back to their affine coordinates, infinity columns preserved."""
    import numpy as np
    import jax.numpy as jnp
    from distributed_plonk_tpu.constants import Q_MOD, FQ_MONT_R
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs, limbs_to_ints

    pts = _rand_points(6) + [None, None]
    zs = [RNG.randrange(2, Q_MOD) for _ in range(len(pts))]
    X, Y, Z = [], [], []
    for pt, z in zip(pts, zs):
        if pt is None:
            X.append(0); Y.append(0); Z.append(0)
        else:
            X.append(pt[0] * z * z % Q_MOD)
            Y.append(pt[1] * z * z * z % Q_MOD)
            Z.append(z)
    to_mont = lambda vs: ints_to_limbs([v * FQ_MONT_R % Q_MOD for v in vs], 24)
    jac = tuple(jnp.asarray(to_mont(v)) for v in (X, Y, Z))
    ax, ay, inf = CJ.batch_to_affine(jac)
    inv_r = pow(FQ_MONT_R, Q_MOD - 2, Q_MOD)
    ax_i = [v * inv_r % Q_MOD for v in limbs_to_ints(np.asarray(ax))]
    ay_i = [v * inv_r % Q_MOD for v in limbs_to_ints(np.asarray(ay))]
    for k, pt in enumerate(pts):
        if pt is None:
            assert bool(np.asarray(inf)[k])
        else:
            assert not bool(np.asarray(inf)[k])
            assert (ax_i[k], ay_i[k]) == pt, k


def test_msm_signed_path_matches_oracle(monkeypatch):
    """The c=8 signed pipeline (32x128) must keep oracle coverage even
    though the single-chip default is now c=7 — the mesh context
    (msm_mesh.py) still runs c=8 unconditionally. Duplicate bases force
    the P==Q fallback inside the scan, and the edge scalars cover digit
    0 / +-max recodings."""
    monkeypatch.setattr(msm_jax.MsmContext, "_C_BATCH", 8)
    n = 256
    distinct = _rand_points(30)
    bases = (distinct * 9)[:n - 2] + [None, None]
    scalars = ([RNG.randrange(R_MOD) for _ in range(n - 4)]
               + [0, 1, R_MOD - 1, 128])
    ctx = msm_jax.MsmContext(bases)
    assert ctx.signed and ctx.c_batch == 8
    assert ctx.msm(scalars) == C.g1_msm(bases, scalars)


def test_signed_recode_roundtrip():
    """Packed signed digits reconstruct the scalar exactly."""
    import numpy as np

    for s in [0, 1, 127, 128, 255, 256, R_MOD - 1,
              RNG.randrange(R_MOD), RNG.randrange(R_MOD)]:
        packed = msm_jax.signed_digits_of_scalars([s], 1)
        digits = packed.astype(np.int64)[:, 0] - 128
        assert sum(int(d) << (8 * w) for w, d in enumerate(digits)) == s
        assert (np.abs(digits) <= 128).all()


def test_signed7_recode_roundtrip():
    """c=7 packed signed digits (37 windows, bias 64, limb-straddling
    extraction) reconstruct the scalar exactly."""
    import numpy as np

    for s in [0, 1, 63, 64, 127, 128, (1 << 254) + 12345, R_MOD - 1,
              RNG.randrange(R_MOD), RNG.randrange(R_MOD)]:
        packed = msm_jax.signed_digits7_of_scalars([s], 1)
        assert packed.shape == (msm_jax.W7, 1)
        digits = packed.astype(np.int64)[:, 0] - 64
        assert sum(int(d) << (7 * w) for w, d in enumerate(digits)) == s
        assert (np.abs(digits) <= 64).all()


def test_msm_c7_matches_oracle(monkeypatch):
    """DPT_MSM_C=7 engages the 37x64 signed pipeline end to end (digit
    extraction across limb boundaries, 64-bucket planes, ceil-window
    finish with the non-power-of-two pairwise tree)."""
    monkeypatch.setattr(msm_jax.MsmContext, "_C_BATCH", 7)
    n = 256
    distinct = _rand_points(30)
    bases = (distinct * 9)[:n - 2] + [None, None]
    scalars = ([RNG.randrange(R_MOD) for _ in range(n - 4)]
               + [0, 1, R_MOD - 1, 64])
    ctx = msm_jax.MsmContext(bases)
    assert ctx.c_batch == 7 and ctx.signed
    assert ctx.msm(scalars) == C.g1_msm(bases, scalars)
    # device digit extraction agrees with the host recode
    import numpy as np
    import jax.numpy as jnp
    from distributed_plonk_tpu.constants import FR_MONT_R
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs

    h = jnp.asarray(ints_to_limbs(
        [s * FR_MONT_R % R_MOD for s in scalars], 16))
    dev = np.asarray(msm_jax.signed_digits7_from_mont(h, ctx.padded_n))
    host = msm_jax.signed_digits7_of_scalars(scalars, ctx.padded_n)
    assert np.array_equal(dev, host)
