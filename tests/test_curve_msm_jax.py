"""Device G1 kernels + MSM vs the curve.py oracle."""

import random

import jax
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import curve_jax as CJ
from distributed_plonk_tpu.backend import msm_jax

RNG = random.Random(0xC0FFEE)


def _rand_points(n):
    return [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n)]


def test_jac_add_double_random():
    n = 16
    ps = _rand_points(n)
    qs = _rand_points(n)
    dev_p = CJ.affine_to_device(ps)
    dev_q = CJ.affine_to_device(qs)
    add_fn = jax.jit(CJ.jac_add)
    dbl_fn = jax.jit(CJ.jac_double)
    got_add = CJ.device_to_affine(add_fn(dev_p, dev_q))
    got_dbl = CJ.device_to_affine(dbl_fn(dev_p))
    assert got_add == [C.g1_add_affine(p, q) for p, q in zip(ps, qs)]
    assert got_dbl == [C.g1_add_affine(p, p) for p in ps]


def test_jac_add_edge_cases():
    p = _rand_points(1)[0]
    q = _rand_points(1)[0]
    lhs = [p, p, p, None, None, p]
    rhs = [p, C.g1_neg(p), None, p, None, q]
    dev_l = CJ.affine_to_device(lhs)
    dev_r = CJ.affine_to_device(rhs)
    got = CJ.device_to_affine(jax.jit(CJ.jac_add)(dev_l, dev_r))
    assert got == [C.g1_add_affine(a, b) for a, b in zip(lhs, rhs)]


@pytest.mark.parametrize("n", [64])
def test_msm_matches_oracle(n):
    bases = _rand_points(n - 2) + [None, None]  # infinity padding like the SRS
    scalars = ([RNG.randrange(R_MOD) for _ in range(n - 4)]
               + [0, 1, R_MOD - 1, RNG.randrange(R_MOD)])
    got = msm_jax.msm(bases, scalars)
    assert got == C.g1_msm(bases, scalars)


def test_msm_short_scalars_and_reuse():
    bases = _rand_points(32)
    ctx = msm_jax.MsmContext(bases)
    s1 = [RNG.randrange(R_MOD) for _ in range(20)]  # shorter than bases
    s2 = [RNG.randrange(R_MOD) for _ in range(32)]
    assert ctx.msm(s1) == C.g1_msm(bases[:20], s1)
    assert ctx.msm(s2) == C.g1_msm(bases, s2)
