"""Round-pipelined multi-job proving tests (prover.prove_pipelined +
the pool's coalesced routing).

The hard contract pinned here: jobs advancing through the five round
stages STAGGERED — one member's device launches overlapping the others'
host transcript/checkpoint work — produce proof bytes BYTE-IDENTICAL to
sequential proves, at every depth, with mixed per-job blinding RNGs and
MIXED CIRCUIT KINDS (per-member proving keys). Plus the failure-domain
semantics at the stage latches: DPT_PIPELINE=0 is a bit-parity escape
hatch; a member killed mid-pipeline resumes ALONE from its round
snapshot (no round-1 re-prove) while the others complete in-flight; a
drain parks EVERY member at its own next latch, each resumable to the
same bytes.

Everything runs the host oracle backend at tiny domains (jax-free), so
the module lives in the fast/chaos tier.
"""

import random

import pytest

from distributed_plonk_tpu import prover
from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.checkpoint import ProverCheckpoint
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove, prove_pipelined
from distributed_plonk_tpu.service import ProofService
from distributed_plonk_tpu.service import placement as PL
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit)

# mixed kinds: different domain sizes AND different proving keys, so the
# pipeline is exercised with per-member pks (not one shared key)
MIXED = [{"kind": "toy", "gates": 16, "seed": 4100},
         {"kind": "range", "bits": 8, "count": 2, "seed": 4101},
         {"kind": "toy", "gates": 16, "seed": 4102},
         {"kind": "range", "bits": 8, "count": 2, "seed": 4103}]


def _keys(spec_obj, _cache={}):
    s = JobSpec.from_wire(spec_obj)
    key = (s.kind, tuple(sorted(s.params.items())))
    if key not in _cache:
        _cache[key] = build_bucket_keys(s)[1]
    return s, _cache[key]


def _sequential_proof(spec_obj):
    """Uninterrupted single prove of a spec — the byte oracle."""
    s, pk = _keys(spec_obj)
    return serialize_proof(prove(random.Random(s.seed), build_circuit(s),
                                 pk, PythonBackend()))


def _members(specs):
    rngs, ckts, pks = [], [], []
    for spec in specs:
        s, pk = _keys(spec)
        rngs.append(random.Random(s.seed))
        ckts.append(build_circuit(s))
        pks.append(pk)
    return rngs, ckts, pks


# --- byte-identity across depths, mixed kinds --------------------------------

@pytest.mark.parametrize("depth", [1, 2, 4])
def test_pipeline_byte_identity(depth):
    """Depth-D pipelined prove of 4 mixed-kind jobs == 4 sequential
    proves, byte for byte. The depth-4 run also checks the stage
    observer saw the pipeline actually fill past one member."""
    oracle = [_sequential_proof(s) for s in MIXED]
    events = []
    rngs, ckts, pks = _members(MIXED)
    proofs, errors = prove_pipelined(rngs, ckts, pks, PythonBackend(),
                                     depth=depth, observer=events.append)
    assert errors == [None] * len(MIXED)
    assert [serialize_proof(p) for p in proofs] == oracle
    assert len(events) == 5 * len(MIXED)  # one per member stage finalize
    for ev in events:
        assert {"round", "depth", "stage_wait_s",
                "device_idle_s"} <= set(ev)
    if depth >= 2:
        assert max(ev["depth"] for ev in events) >= 2


def test_pipeline_knob_off_parity(monkeypatch):
    """DPT_PIPELINE=0: prove_pipelined degrades to the sequential
    per-job path — same signature, identical bytes."""
    monkeypatch.setattr(prover, "PIPELINE", False)
    oracle = [_sequential_proof(s) for s in MIXED[:2]]
    rngs, ckts, pks = _members(MIXED[:2])
    proofs, errors = prove_pipelined(rngs, ckts, pks, PythonBackend(),
                                     depth=4)
    assert errors == [None, None]
    assert [serialize_proof(p) for p in proofs] == oracle


# --- stage-latch failure domains ---------------------------------------------

class _Killed(Exception):
    pass


class _Drained(Exception):
    pass


class _LatchCheckpoint(ProverCheckpoint):
    """Checkpoint guard that raises `exc` right after the `at_round`
    snapshot is durable — the same crash point the pool's kill/drain
    guards model. Records every save's round number."""

    def __init__(self, path, at_round=None, exc=None):
        super().__init__(path)
        self.at_round = at_round
        self.exc = exc
        self.saved_rounds = []

    def save(self, round_no, *args, **kwargs):
        super().save(round_no, *args, **kwargs)
        self.saved_rounds.append(round_no)
        if self.exc is not None and round_no == self.at_round:
            raise self.exc(f"latch fired after round {round_no}")


def test_pipeline_member_kill_resumes_alone(tmp_path):
    """A member-local failure at its round-2 latch takes down ONLY that
    member: the others complete in-flight (same call, correct bytes),
    and the victim's solo retry RESUMES from its snapshot — saving only
    rounds 3-4, never re-proving 1-2 — to byte-identical bytes."""
    specs = MIXED[:3]
    oracle = [_sequential_proof(s) for s in specs]
    cks = [_LatchCheckpoint(str(tmp_path / f"m{i}.npz"),
                            at_round=2 if i == 1 else None,
                            exc=_Killed if i == 1 else None)
           for i in range(len(specs))]
    rngs, ckts, pks = _members(specs)
    proofs, errors = prove_pipelined(rngs, ckts, pks, PythonBackend(),
                                     checkpoints=cks, depth=4)
    assert proofs[0] is not None and proofs[2] is not None
    assert proofs[1] is None and isinstance(errors[1], _Killed)
    assert [serialize_proof(p) for p in (proofs[0], proofs[2])] == \
        [oracle[0], oracle[2]]
    # the victim's snapshot is durable at its latch; the solo retry
    # resumes at round 3 (the pool's single-job retry path)
    assert cks[1].saved_rounds == [1, 2]
    s, pk = _keys(specs[1])
    resume_ck = _LatchCheckpoint(cks[1].path)
    proof = prove(random.Random(s.seed), build_circuit(s), pk,
                  PythonBackend(), checkpoint=resume_ck)
    assert serialize_proof(proof) == oracle[1]
    assert resume_ck.saved_rounds == [3, 4]  # resumed, never re-proved 1-2
    assert not resume_ck.has_snapshot()  # cleared on success


def test_pipeline_drain_parks_every_member(tmp_path):
    """An abort_on exception (the pool's drain signal) at one member's
    latch aborts the whole pipeline: every member parks at its OWN next
    stage latch — snapshot durable at its last completed round — and
    each resumes independently to byte-identical bytes."""
    specs = MIXED[:3]
    oracle = [_sequential_proof(s) for s in specs]
    cks = [_LatchCheckpoint(str(tmp_path / f"d{i}.npz"),
                            at_round=2 if i == 0 else None,
                            exc=_Drained if i == 0 else None)
           for i in range(len(specs))]
    rngs, ckts, pks = _members(specs)
    with pytest.raises(_Drained):
        prove_pipelined(rngs, ckts, pks, PythonBackend(),
                        checkpoints=cks, abort_on=(_Drained,), depth=4)
    # every member parked at its own latch: whatever rounds it finished
    # are snapshot, in order, nothing past round 2 (the drain point)
    for ck in cks:
        assert ck.saved_rounds == list(range(1, len(ck.saved_rounds) + 1))
    assert cks[0].saved_rounds == [1, 2]
    for spec, ck, want in zip(specs, cks, oracle):
        s, pk = _keys(spec)
        proof = prove(random.Random(s.seed), build_circuit(s), pk,
                      PythonBackend(), checkpoint=ProverCheckpoint(ck.path))
        assert serialize_proof(proof) == want


# --- service routing: queue coalescing fills the pipeline --------------------

def test_service_coalesces_queue_into_pipeline(monkeypatch):
    """With shape-batching OFF (jobs arrive as single dispatch units),
    a worker that pops one unit coalesces its queue neighbors into a
    pipelined attempt — small-shape traffic fills the pipeline without
    the placement layer forming a batch — and every proof still matches
    the sequential oracle."""
    monkeypatch.setattr(PL, "BATCH_PROVE", False)
    specs = [dict(MIXED[i % 2], seed=4200 + i) for i in range(4)]
    svc = ProofService(port=0, prover_workers=1)
    jobs = [svc.submit_local(s) for s in specs]  # queued before start
    svc.start()
    try:
        for j in jobs:
            assert j.done_event.wait(timeout=180), j.status()
            assert j.state == "done"
        ctr = svc.metrics.snapshot()["counters"]
        # coalesced singles are NOT shape batches
        assert "batch_proves" not in ctr
        assert ctr.get("pipelined_proves", 0) >= 1
        assert ctr.get("pipelined_jobs", 0) >= 2
        for spec, job in zip(specs, jobs):
            assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


def test_service_pipeline_off_routes_sequential(monkeypatch):
    """DPT_PIPELINE=0 at the service layer: no coalescing, no pipelined
    attempts — the historical per-job path, identical bytes."""
    monkeypatch.setattr(prover, "PIPELINE", False)
    monkeypatch.setattr(PL, "BATCH_PROVE", False)
    specs = [dict(MIXED[0], seed=4300 + i) for i in range(2)]
    svc = ProofService(port=0, prover_workers=1)
    jobs = [svc.submit_local(s) for s in specs]
    svc.start()
    try:
        for j in jobs:
            assert j.done_event.wait(timeout=180), j.status()
            assert j.state == "done"
        ctr = svc.metrics.snapshot()["counters"]
        assert "pipelined_proves" not in ctr
        for spec, job in zip(specs, jobs):
            assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()
