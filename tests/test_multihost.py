"""Multi-host (DCN) proof: two REAL processes join one jax.distributed
mesh and run the framework's collectives across it.

The reference's only multi-host evidence is its 2-host LAN deployment
(/root/reference/config/network.json:1-10, src/worker.rs:441-536); this is
the jax.distributed multi-controller analog, runnable in CI without
hardware: each subprocess owns 4 virtual CPU devices
(xla_force_host_platform_device_count), process 0 is the coordinator
(network.json analog), and the 8-device global mesh runs the 4-step
cross-shard NTT (lax.all_to_all over what would be DCN) plus a sharded
MSM — asserting bit-identity against the host oracle in every process.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

_CHILD = r"""
import os, random, sys
sys.path.insert(0, {repo!r})
import jax
from distributed_plonk_tpu.parallel.mesh import init_multihost, make_mesh
from distributed_plonk_tpu.parallel.ntt_mesh import MeshNttPlan
from distributed_plonk_tpu.parallel.msm_mesh import MeshMsmContext
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD

pid = int(sys.argv[1])
nproc, ndev = init_multihost(sys.argv[2], 2, pid)
assert nproc == 2, nproc
assert ndev == 8, ndev  # 4 local virtual cpu devices per process

mesh = make_mesh(8)
rng = random.Random(21)
n = 64
domain = P.Domain(n)
values = [rng.randrange(R_MOD) for _ in range(n)]
plan = MeshNttPlan(mesh, n)
coeffs = plan.run_ints(values, inverse=True)
assert coeffs == P.ifft(domain, values), "multihost mesh iNTT mismatch"

bases = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD)) for _ in range(16)]
scalars = [rng.randrange(R_MOD) for _ in range(16)]
ctx = MeshMsmContext(mesh, bases)
assert ctx.msm(scalars) == C.g1_msm(bases, scalars), "multihost MSM mismatch"
print("MULTIHOST_OK", pid, flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_distributed_mesh():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_NUM_PROCESSES", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD.format(repo=str(REPO)), str(pid),
             coord],
            cwd=str(REPO), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=900)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MULTIHOST_OK" in out, (out, err[-1500:])
