"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path): JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8
must be set before jax is imported anywhere — hence this env setup sits at
the very top of conftest, before any project import.
"""

import os

# Force CPU even when the environment preselects the real TPU platform
# (JAX_PLATFORMS=axon): per-op tunnel latency makes eager tests unusable, and
# the sharding tests need the 8-device virtual mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The persistent compilation cache itself is configured by
# distributed_plonk_tpu.backend.field_jax at import time.

# NOTE: a site-installed TPU plugin (axon) may override JAX_PLATFORMS at
# interpreter startup, in which case single-device tests run on the real
# chip (with its remote-compile service) — that is deliberate extra
# coverage of the TPU lowering. The mesh tests pin platform="cpu"
# explicitly, so the 8-device virtual mesh is exercised either way.

import pytest


def build_test_circuit():
    """Small circuit exercising every selector type."""
    from distributed_plonk_tpu.circuit import PlonkCircuit

    ckt = PlonkCircuit()
    x = ckt.create_public_variable(5)
    y = ckt.create_public_variable(11)
    s = ckt.add(x, y)
    p = ckt.mul(x, y)
    ckt.power5(s)
    l = ckt.lc([x, y, s, p], [2, 3, 5, 7])
    d = ckt.add_constant(l, 42)
    m = ckt.mul_constant(d, 9)
    ckt.sub(m, p)
    ckt.enforce_ecc_product(x, y, s, p, ckt.one_var, 5 * 11 * 16 * 55)
    return ckt


@pytest.fixture(scope="session")
def proven():
    """Finalized test circuit + keys + host-oracle proof (seed 1)."""
    import random
    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.backend.python_backend import PythonBackend

    ckt = build_test_circuit()
    ok, row = ckt.check_satisfiability()
    assert ok, f"unsatisfied at row {row}"
    ckt.finalize()
    ok, row = ckt.check_satisfiability()
    assert ok, f"unsatisfied after finalize at row {row}"
    srs = kzg.universal_setup(ckt.n + 3, tau=0xDEADBEEF)
    pk, vk = kzg.preprocess(srs, ckt)
    proof = prove(random.Random(1), ckt, pk, PythonBackend())
    return ckt, pk, vk, proof
