"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path): JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8
must be set before jax is imported anywhere.
"""

import os

# Force CPU even when the environment preselects the real TPU platform
# (JAX_PLATFORMS=axon): per-op tunnel latency makes eager tests unusable, and
# the sharding tests need the 8-device virtual mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# The persistent compilation cache itself is configured by
# distributed_plonk_tpu.backend.field_jax at import time.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
