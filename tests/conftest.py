"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path): JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8
must be set before jax is imported anywhere — hence this env setup sits at
the very top of conftest, before any project import.
"""

import os

# Force CPU even when the environment preselects the real TPU platform
# (JAX_PLATFORMS=axon): per-op tunnel latency makes eager tests unusable, and
# the sharding tests need the 8-device virtual mesh. Also scrub the relay
# trigger variables entirely — round-2 post-mortem: with the relay dead,
# platform discovery blocks forever at ~0 CPU, so a suite that merely pins
# JAX_PLATFORMS=cpu but leaves PALLAS_AXON_POOL_IPS set can still hang in
# subprocesses it spawns (worker fleet, dryrun). Tests that need the real
# chip must opt in explicitly.
for _k in list(os.environ):
    if _k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
        os.environ.pop(_k)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize imports jax at interpreter startup, so jax's config
# already captured JAX_PLATFORMS=axon before this file ran — the env
# assignment above only covers subprocesses. Pin the in-process config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The persistent compilation cache itself is configured by
# distributed_plonk_tpu.backend.field_jax at import time.

import pytest

# pure-host tier (pytest -m "host and not slow", sub-minute): modules whose
# tests never trigger an XLA compile — the cheap CI/judging tier the full
# "not slow" smoke tier (minutes of cold compiles) cannot provide
_HOST_TIER = {
    "test_transcript", "test_fields", "test_poly", "test_curve",
    "test_encoding", "test_rescue_merkle", "test_prove_verify",
    "test_proof_golden", "test_imports", "test_checkpoint",
    "test_service", "test_store", "test_runtime_faults",
    "test_membership", "test_integrity", "test_fleet_obs",
    "test_autoscale",
}


def pytest_collection_modifyitems(items):
    for item in items:
        if item.module.__name__ in _HOST_TIER:
            item.add_marker(pytest.mark.host)
    # run the cheap host tier FIRST (stable within each group): the smoke
    # tier runs under a wall-clock budget, and front-loading the sub-second
    # host tests means a budget-bound run still reports the entire host
    # surface (prover, checkpoint, service, transcript) before the
    # multi-minute XLA-compile modules start burning the clock
    items.sort(key=lambda it: it.module.__name__ not in _HOST_TIER)


def build_test_circuit():
    """Small circuit exercising every selector type."""
    from distributed_plonk_tpu.circuit import PlonkCircuit

    ckt = PlonkCircuit()
    x = ckt.create_public_variable(5)
    y = ckt.create_public_variable(11)
    s = ckt.add(x, y)
    p = ckt.mul(x, y)
    ckt.power5(s)
    l = ckt.lc([x, y, s, p], [2, 3, 5, 7])
    d = ckt.add_constant(l, 42)
    m = ckt.mul_constant(d, 9)
    ckt.sub(m, p)
    ckt.enforce_ecc_product(x, y, s, p, ckt.one_var, 5 * 11 * 16 * 55)
    return ckt


@pytest.fixture(scope="session")
def proven():
    """Finalized test circuit + keys + host-oracle proof (seed 1)."""
    import random
    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.backend.python_backend import PythonBackend

    ckt = build_test_circuit()
    ok, row = ckt.check_satisfiability()
    assert ok, f"unsatisfied at row {row}"
    ckt.finalize()
    ok, row = ckt.check_satisfiability()
    assert ok, f"unsatisfied after finalize at row {row}"
    srs = kzg.universal_setup(ckt.n + 3, tau=0xDEADBEEF)
    pk, vk = kzg.preprocess(srs, ckt)
    proof = prove(random.Random(1), ckt, pk, PythonBackend())
    return ckt, pk, vk, proof
