"""Self-healing fleet tests (ISSUE 12): dynamic membership, supervised
respawn, warm rejoin.

Acceptance surface: a 3-worker fleet with one member SIGKILLed mid-prove
heals back to full width through supervisor respawn + JOIN re-admission
with proof bytes IDENTICAL to the host oracle; a worker joining mid-life
widens the sharded FFT at the next phase boundary; frames planned
against an older roster are rejected as stale; a crash-looping slot hits
the flap cap instead of being respawned forever; and a joiner with a
store warm-rejoins (bucket keys + jax compile-cache entries pulled from
roster peers, zero key builds) and is auto-discovered as a bucket-cache
peer by an attached proof service.

Wait discipline: every wait is event-driven against a generous deadline
(these run inside ci.sh chaos and tier-1 under load), never a fixed
sleep.
"""

import os
import random
import time

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.runtime import protocol
from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
from distributed_plonk_tpu.runtime.health import LivenessTracker
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
from distributed_plonk_tpu.service.metrics import Metrics

RNG = random.Random(0x5E1F)
REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
_LOAD_BUDGET_S = float(os.environ.get("DPT_TEST_WAIT_S", "120"))


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    monkeypatch.setattr(WorkerHandle, "RECONNECT_TRIES", 2)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_BASE_S", 0.01)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_MAX_S", 0.05)
    monkeypatch.setattr(WorkerHandle, "TIMEOUT_MS", 120000)


def _wait_for(cond, timeout_s=None, interval=0.05, msg=""):
    deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
    while True:
        got = cond()
        if got:
            return got
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {msg or cond}")
        time.sleep(interval)


def _member_dispatcher(metrics=None, faults=None, breaker_k=2):
    """Empty dispatcher + fast tracker + membership plane armed (the
    tracker is swapped BEFORE any join, so appended workers line up)."""
    metrics = metrics or Metrics()
    d = Dispatcher(NetworkConfig([]), metrics=metrics, faults=faults)
    d.tracker = LivenessTracker(0, breaker_k=breaker_k, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    mserver = d.enable_membership()
    return d, mserver, metrics


def _wait_width(d, n, usable=True):
    _wait_for(lambda: len(d.workers) >= n
              and (not usable or len(d.tracker.usable_set()) >= n),
              msg=f"fleet width {n}")


def _shutdown(d, sup=None):
    if sup is not None:
        sup.stop()
    try:
        d.shutdown()
    finally:
        d.pool.shutdown(wait=False)


def _supervised(n, metrics=None, faults=None, store_dirs=None, **sup_kw):
    d, mserver, metrics = _member_dispatcher(metrics=metrics, faults=faults)
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=n, backend="python",
                           store_dirs=store_dirs, metrics=metrics, cwd=REPO,
                           **sup_kw).start()
    if faults is not None:
        faults.proc_kill_cb = sup.proc_killer(d)
    _wait_width(d, n)
    return d, sup, metrics


# --- membership basics --------------------------------------------------------

def test_join_mid_life_replans_fft_up_byte_identity(proven):
    """A worker joining a live 2-wide fleet widens the next sharded FFT
    to 3 (the joiner serves stage work), and a full distributed prove on
    the widened fleet is byte-identical to the host oracle."""
    from distributed_plonk_tpu.prover import prove

    ckt, pk, vk, proof_host = proven
    d, sup, metrics = _supervised(2)
    try:
        n = 64
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        want = P.ifft(P.Domain(n), values)
        assert d.fft_dist(values, inverse=True) == want
        epoch_before = d.epoch

        # grow the supervisor by one slot at runtime: same JOIN path a
        # brand-new host would take
        assert sup.add_slot() == 2
        _wait_width(d, 3)
        assert d.epoch > epoch_before

        # next phase boundary plans over the wider fleet: the joiner
        # serves sharded-FFT frames (served-request counters say so)
        assert d.fft_dist(values, inverse=True) == want
        stats = _wait_for(
            lambda: d.workers[2].probe(timeout_ms=5000), interval=0.2,
            msg="joiner probe")
        assert stats["epoch"] >= d.epoch - 1
        served = d.stats()[2]
        assert served.get(str(protocol.FFT_INIT), 0) >= 1
        assert served.get(str(protocol.FFT2), 0) >= 1

        # and the whole prove (FFTs + range-sharded MSM across all 3)
        # is byte-identical to the host oracle
        proof = prove(random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof.opening_proof == proof_host.opening_proof
        assert proof.wires_poly_comms == proof_host.wires_poly_comms
        assert proof.split_quot_poly_comms \
            == proof_host.split_quot_poly_comms
    finally:
        _shutdown(d, sup)


def test_stale_epoch_frame_rejected():
    """A worker whose roster moved on rejects FFT_INIT frames planned
    against an older epoch (loudly — ERR, not silent misrouting); epoch
    0 (membership-less sender) and the current epoch stay accepted."""
    d, sup, metrics = _supervised(1)
    try:
        w = d.workers[0]
        cur = _wait_for(lambda: w.probe(timeout_ms=5000), interval=0.2,
                        msg="probe")["epoch"]
        assert cur >= 1

        # push a newer roster directly: worker adopts it
        newer = cur + 5
        roster = protocol.encode_json(
            {"epoch": newer, "workers": [f"{w.host}:{w.port}"]})
        w.call(protocol.ROSTER, roster, traced=False)

        def init(epoch):
            return w.call(protocol.FFT_INIT, protocol.encode_fft_init(
                RNG.getrandbits(63), False, False, 16, 4, 4, 0, 4,
                [(0, 4)], epoch=epoch))

        with pytest.raises(RuntimeError, match="stale epoch"):
            init(newer - 1)
        # a frame from AHEAD of this worker's roster is equally
        # unservable (it references peers the worker's table lacks —
        # the worker missed a push): loud rejection, not an IndexError
        with pytest.raises(RuntimeError, match="stale epoch"):
            init(newer + 3)
        init(0)        # pre-membership sender: accepted
        init(newer)    # current plan: accepted
        # an OLDER roster push is ignored (epochs only move forward)
        w.call(protocol.ROSTER, protocol.encode_json(
            {"epoch": 1, "workers": []}), traced=False)
        assert w.probe(timeout_ms=5000)["epoch"] == newer
    finally:
        _shutdown(d, sup)


# --- supervision --------------------------------------------------------------

def test_supervisor_respawns_and_rejoins_in_place():
    """SIGKILL a supervised worker: the supervisor respawns it, it
    re-JOINs under the SAME fleet index (no special re-entry path), the
    breaker re-admits it, and MSM routing rebalances back onto it."""
    d, sup, metrics = _supervised(2)
    try:
        n = 32
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        want = C.g1_msm(bases, scalars)
        d.init_bases(bases)
        assert d.msm(scalars) == want

        width_before = len(d.workers)
        sup.kill(1)
        # supervisor detects the death and respawns; the rejoin lands on
        # the same index — fleet table does NOT grow
        _wait_for(lambda: metrics.snapshot()["counters"].get(
            "membership_rejoins", 0) >= 1, msg="rejoin")
        _wait_width(d, 2)
        assert len(d.workers) == width_before
        snap = metrics.snapshot()["counters"]
        assert snap.get("worker_respawns", 0) >= 1
        # MSM correct regardless of when the rebalance lands; the
        # re-provision eventually routes range 1 to worker 1 again
        assert d.msm(scalars) == want
        _wait_for(lambda: 1 not in d._adopted, msg="re-provision")
        assert d.msm(scalars) == want
    finally:
        _shutdown(d, sup)


def test_supervisor_flap_cap_gives_up_and_leaves():
    """A crash-looping slot is respawned with backoff at most flap_cap
    times inside the window, then marked FAILED and LEAVEd from the
    fleet — never respawned forever."""
    import sys
    d, mserver, metrics = _member_dispatcher()
    crash = [sys.executable, "-c", "raise SystemExit(1)"]
    sup = WorkerSupervisor(
        "127.0.0.1", mserver.port, n=1, metrics=metrics, cwd=REPO,
        spawn_cmd=lambda i, slot: crash,
        probe_interval_s=0.05, backoff_base_s=0.02, backoff_max_s=0.1,
        flap_cap=3, flap_window_s=60).start()
    try:
        _wait_for(lambda: metrics.snapshot()["counters"].get(
            "worker_flap_capped", 0) == 1, msg="flap cap")
        assert sup.snapshot()[0]["failed"]
        spawned = len(sup.slots[0].spawn_times)
        assert spawned <= 3
        # respawning has genuinely stopped
        time.sleep(0.5)
        assert len(sup.slots[0].spawn_times) == spawned
        # the crash-looper never joined, so the fleet never saw it; a
        # slot that HAD joined would be LEAVEd (membership_leaves) — the
        # LEAVE here is a no-op lookup error, swallowed best-effort
        assert len(d.workers) == 0
    finally:
        _shutdown(d, sup)


def test_flap_cap_after_join_leaves_fleet():
    """A member that joins, then keeps dying, is declared gone at the
    flap cap: LEAVE bumps the epoch and opens its breaker so the fleet
    stops routing to the corpse."""
    d, sup, metrics = _supervised(
        1, probe_interval_s=0.05, backoff_base_s=0.02, backoff_max_s=0.1,
        flap_cap=2, flap_window_s=3600.0)
    try:
        epoch_before = d.epoch

        def _flapped():
            s = sup.snapshot()[0]
            if s["failed"]:
                return True
            if s["alive"]:
                sup.kill(0)  # keep the crash loop going until the cap
            return False
        _wait_for(_flapped, interval=0.2, msg="flap cap")
        snap = metrics.snapshot()["counters"]
        assert snap.get("worker_flap_capped", 0) == 1
        _wait_for(lambda: metrics.snapshot()["counters"].get(
            "membership_leaves", 0) >= 1, msg="leave")
        assert d.epoch > epoch_before
        assert not d.tracker.usable(0)
        # a LEAVEd member is never revived by the probe planes, even if
        # its address still answers (start an unrelated listener there):
        # only an explicit JOIN brings a decommissioned slot back
        assert d.membership.is_left(0)
        d.tracker.force_probe(0)
        d._maybe_readmit()
        assert not d.tracker.usable(0)
        assert list(d._probe_readmit([0])) == []
    finally:
        _shutdown(d, sup)


# --- warm rejoin + auto-discovery ---------------------------------------------

def test_warm_rejoin_pulls_artifacts_and_compile_cache(tmp_path):
    """A joiner with an empty store pulls bucket-key artifacts AND jax
    persistent-compile-cache entries from the roster's store peers
    (STORE_LIST + STORE_FETCH), reports warm_rejoin_s, and its HEALTH
    shows the sync. Zero key builds anywhere."""
    from distributed_plonk_tpu.service.jobs import (JobSpec,
                                                    build_bucket_keys,
                                                    shape_key)
    from distributed_plonk_tpu.store import ArtifactStore
    from distributed_plonk_tpu.store import keycache as KC

    # warm peer store: real bucket keys + fake compile-cache entries
    warm_dir = str(tmp_path / "warm")
    warm = ArtifactStore(warm_dir)
    spec = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 5})
    srs, pk, vk = build_bucket_keys(spec)
    KC.store_bucket(warm, shape_key(spec), srs, pk, vk)
    warm.jax_cache_write("fp/exec1.bin", b"compiled-exec-1")
    warm.jax_cache_write("fp/exec2.bin", b"compiled-exec-2")

    cold_dir = str(tmp_path / "cold")
    # the warm peer joins FIRST (so its store is in the roster the cold
    # joiner receives), then the cold worker scales in
    d, sup, metrics = _supervised(1, store_dirs=[warm_dir])
    try:
        assert sup.add_slot(store_dir=cold_dir) == 1
        _wait_width(d, 2)
        # worker 1 (cold store) warm-rejoined from worker 0 (warm store)
        snap = _wait_for(
            lambda: (d.workers[1].probe(timeout_ms=5000) or {}).get("warm"),
            interval=0.2, msg="warm rejoin stats")
        assert snap["artifacts"] == 1
        assert snap["jax_cache_files"] == 2
        cold = ArtifactStore(cold_dir)
        hit = KC.load_bucket(cold, shape_key(spec))
        assert hit is not None and hit[2].domain_size == vk.domain_size
        assert cold.jax_cache_read("fp/exec1.bin") == b"compiled-exec-1"
        # the ready report landed the warm_rejoin_s observation
        _wait_for(lambda: metrics.snapshot()["counters"].get(
            "warm_rejoins", 0) >= 2, msg="ready reports")
        assert "warm_rejoin_s" in metrics.snapshot()["histograms"]
    finally:
        _shutdown(d, sup)


def test_join_store_auto_registered_as_bucket_peer(tmp_path, monkeypatch):
    """ROADMAP direction-2 auto-discovery: a worker that JOINs with a
    warm store becomes a BucketCache peer of an attached proof service —
    the service then serves a seen shape with ZERO key builds (build
    forbidden by monkeypatch), entirely from the joiner's store."""
    import json
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service import jobs as J
    from distributed_plonk_tpu.store import ArtifactStore
    from distributed_plonk_tpu.store import keycache as KC

    warm_dir = str(tmp_path / "warm")
    warm = ArtifactStore(warm_dir)
    spec = {"kind": "toy", "gates": 16, "seed": 5}
    sp = J.JobSpec.from_wire(spec)
    srs, pk, vk = J.build_bucket_keys(sp)
    KC.store_bucket(warm, J.shape_key(sp), srs, pk, vk)

    d, mserver, metrics = _member_dispatcher()
    svc = ProofService(port=0, prover_workers=1,
                       store_dir=str(tmp_path / "svc")).start()
    svc.attach_membership(d.membership)
    sup = None
    try:
        assert svc.buckets.peers == []
        sup = WorkerSupervisor("127.0.0.1", mserver.port, n=1,
                               backend="python", store_dirs=[warm_dir],
                               metrics=metrics, cwd=REPO).start()
        _wait_width(d, 1)
        _wait_for(lambda: len(svc.buckets.peers) == 1,
                  msg="peer auto-registration")
        assert svc.buckets.peers[0] == ("127.0.0.1", sup.slots[0].port)

        def _forbidden(*a, **kw):
            raise AssertionError("key build on the warm-peer path")
        monkeypatch.setattr(J, "build_bucket_keys", _forbidden)
        with ServiceClient("127.0.0.1", svc.port) as c:
            jid = c.submit(dict(spec, seed=6))["job_id"]
            st = c.wait(jid, timeout_s=120)
            assert st["state"] == "done", json.dumps(st)
            m = c.metrics()
        assert m["counters"].get("bucket_peer_hits", 0) == 1
        assert m["counters"].get("bucket_peers_added", 0) == 1
        assert m["counters"].get("bucket_misses", 0) == 0
        # a LEAVEd store member is dropped from the peer list (later
        # cold misses must not burn the peer timeout on its corpse)
        d.membership.leave(host="127.0.0.1", port=sup.slots[0].port)
        _wait_for(lambda: svc.buckets.peers == [], msg="peer removal")
    finally:
        svc.shutdown()
        _shutdown(d, sup)


# --- the heal canary ----------------------------------------------------------

def test_self_heal_end_to_end(proven, tmp_path):
    """THE acceptance canary: 3 supervised workers, one SIGKILLed
    mid-FFT1 by the `kill:at=proc` chaos plane. The prove replans and
    finishes byte-identical to the host oracle; the supervisor respawns
    the victim; it re-JOINs in place (warm stats present) and the fleet
    heals back to full width."""
    from distributed_plonk_tpu.prover import prove

    ckt, pk, vk, proof_host = proven
    metrics = Metrics()
    kill_at = []
    faults = FaultInjector(
        [Rule("kill", tag=protocol.FFT1, worker=1, nth=1, plane="proc")],
        metrics=metrics)
    store_dirs = [str(tmp_path / f"w{i}") for i in range(3)]
    d, sup, metrics = _supervised(3, metrics=metrics, faults=faults,
                                  store_dirs=store_dirs)

    proc_kill = sup.proc_killer(d)

    def stamped_kill(i):
        kill_at.append(time.perf_counter())
        proc_kill(i)
    faults.proc_kill_cb = stamped_kill
    try:
        proof = prove(random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof.opening_proof == proof_host.opening_proof
        assert proof.shifted_opening_proof \
            == proof_host.shifted_opening_proof
        assert proof.wires_poly_comms == proof_host.wires_poly_comms
        assert proof.split_quot_poly_comms \
            == proof_host.split_quot_poly_comms
        snap = metrics.snapshot()["counters"]
        assert snap.get("faults_injected_kill", 0) == 1
        assert len(kill_at) == 1

        def _healed():
            return len(d.tracker.usable_set()) == 3 and all(
                w.probe(timeout_ms=2000) is not None for w in d.workers)
        _wait_for(_healed, interval=0.1, msg="heal to full width")
        heal_s = time.perf_counter() - kill_at[0]
        snap = metrics.snapshot()["counters"]
        assert snap.get("worker_respawns", 0) >= 1
        assert snap.get("membership_rejoins", 0) >= 1
        # the respawned member rejoined warm (store sync ran; with only
        # empty peer stores it still reports the stats envelope)
        warm = _wait_for(
            lambda: (d.workers[1].probe(timeout_ms=5000) or {}).get("warm"),
            interval=0.2, msg="warm stats on the rejoined worker")
        assert "warm_rejoin_s" in warm
        # a healed fleet serves a follow-up prove at full width
        proof2 = prove(random.Random(1), ckt, pk,
                       RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof2.opening_proof == proof_host.opening_proof
        assert heal_s < _LOAD_BUDGET_S
    finally:
        _shutdown(d, sup)
