"""Smoke test: every module in the package parses and imports.

Guards against shipping unparseable modules (round-1 regression:
runtime/worker.py was committed with a SyntaxError and the fleet tests
only caught it at fixture collection).
"""

import importlib
import pathlib
import pkgutil

import distributed_plonk_tpu


def test_all_modules_import():
    root = pathlib.Path(distributed_plonk_tpu.__file__).parent
    mods = [distributed_plonk_tpu.__name__]
    for info in pkgutil.walk_packages([str(root)], prefix="distributed_plonk_tpu."):
        mods.append(info.name)
    assert len(mods) > 10
    for name in mods:
        importlib.import_module(name)


def test_graft_entry_parses():
    import ast

    src = pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    ast.parse(src.read_text())
    src = pathlib.Path(__file__).parent.parent / "bench.py"
    ast.parse(src.read_text())
