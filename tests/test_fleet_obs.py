"""Fleet observability plane tests (ISSUE 15 acceptance surface).

Five planes, all jax-free (python-backend workers over real TCP):
- structured-log units: ring semantics, trace filtering, file sink, and
  the LOG01 subsystem-glossary lint;
- fleet metrics: METRICS_FETCH scrape of a live fleet, per-worker
  labelled Prometheus rendering, breaker/suspect awareness;
- wire back-compat: the new METRICS_FETCH/LOG_FETCH/PROFILE tags degrade
  to empty results against an old worker and never kill serving, and a
  new worker answers an unknown tag with ERR on a connection that keeps
  working;
- the ONE-PANE acceptance criterion: a live 3-worker SUPERVISED fleet
  prove with a mid-FFT worker kill yields, from one ObsServer, the
  aggregated dpt_fleet_* series, the /fleet snapshot, a merged
  trace:<job_id> artifact carrying dispatcher/supervisor/worker
  structured log events under the prove's trace id, and a fetchable
  profile:<id> artifact — proof bytes byte-identical throughout;
- the perf-regression gate: normalize/compare units plus the committed
  trajectory staying green (the ci.sh benchcheck contract).
"""

import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_plonk_tpu.obs import fleet as OF
from distributed_plonk_tpu.obs import log as olog
from distributed_plonk_tpu.runtime import native, protocol
from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.trace import Tracer

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPTS = os.path.join(REPO, "scripts")
RNG = random.Random(0x0B515)


def _spawn_workers(tmp_path, n, port_base):
    base = port_base + (os.getpid() % 400) * (n + 1)
    cfg = NetworkConfig([f"127.0.0.1:{base + i}" for i in range(n)])
    cfg_path = str(tmp_path / "network.json")
    cfg.save(cfg_path)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
         str(i), cfg_path, "--backend", "python"], cwd=REPO)
        for i in range(n)]
    deadline = time.time() + 60
    pending = set(range(n))
    while pending and time.time() < deadline:
        for i in sorted(pending):
            h, p = cfg.workers[i]
            if WorkerHandle(h, p).probe(timeout_ms=2000) is not None:
                pending.discard(i)
        if pending:
            time.sleep(0.2)
    assert not pending, f"workers {sorted(pending)} did not come up"
    return cfg, procs


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.wait(timeout=10)


def _shutdown(d):
    for w in d.workers:
        try:
            w.call(protocol.SHUTDOWN, traced=False)
        except Exception:
            pass
        w.close()
    d.pool.shutdown(wait=False)


def _get(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


# --- structured-log plane ----------------------------------------------------

def test_log_buffer_ring_filter_sink(tmp_path):
    buf = olog.LogBuffer(cap=4, proc="t")
    for i in range(6):
        buf.emit("service", "retry", job_id=f"j{i}",
                 trace_id="aa" if i % 2 else None)
    out = buf.fetch()
    assert out["seq"] == 6
    assert [e["seq"] for e in out["events"]] == [3, 4, 5, 6]  # ring cap 4
    # trace filter + since_seq tailing
    assert all(e["trace_id"] == "aa"
               for e in buf.fetch(trace_id="aa")["events"])
    assert [e["seq"] for e in buf.fetch(since_seq=5)["events"]] == [6]
    assert len(buf.fetch(limit=2)["events"]) == 2
    # file sink: one JSON object per line, events recorded after open
    path = buf.open_sink(str(tmp_path / "logs"), proc="t2")
    assert path and os.path.exists(path)
    buf.emit("service", "shed", level="warn", job_id="jx", reason="ttl")
    buf.close_sink()
    lines = [json.loads(line) for line in open(path)]
    assert lines and lines[-1]["event"] == "shed"
    assert lines[-1]["subsystem"] == "service"
    # the glossary the LOG01 lint enforces is parseable and non-trivial
    subs = olog.documented_subsystems()
    assert {"dispatcher", "supervisor", "worker", "service",
            "membership", "integrity", "obs"} <= subs


def test_log01_lint_subsystem_glossary():
    from distributed_plonk_tpu.analysis.lint import lint_source
    bad = ("from distributed_plonk_tpu.obs import log as olog\n"
           "def f():\n"
           "    olog.emit('totally_new_subsystem', 'boom')\n")
    findings = lint_source(bad, kinds=("log",))
    assert any(f.code == "LOG01" for f in findings), findings
    good = bad.replace("totally_new_subsystem", "dispatcher")
    assert not lint_source(good, kinds=("log",))
    # derived subsystems are out of scope (families are a design choice)
    derived = ("def f(name):\n"
               "    emit(name, 'x')\n")
    assert not lint_source(derived, kinds=("log",))
    # the live tree is CLEAN against its own glossary (the ci.sh gate)
    from distributed_plonk_tpu.analysis.lint import run_lints
    assert not [f for f in run_lints() if f.code == "LOG01"]


# --- fleet metrics plane -----------------------------------------------------

def test_metrics_fetch_scrape_render_and_suspect_awareness(tmp_path):
    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD

    cfg, procs = _spawn_workers(tmp_path, 2, 33500)
    d = Dispatcher(cfg)
    try:
        values = [RNG.randrange(R_MOD) for _ in range(16)]
        assert d.ntt(values) == P.fft(P.Domain(16), values)
        entries = d.fleet_metrics()
        assert [e["index"] for e in entries] == [0, 1]
        assert all(e["reachable"] for e in entries)
        snaps = [e["snapshot"] for e in entries]
        assert all(s is not None for s in snaps)
        # the NTT the fleet just served shows up in exactly one worker's
        # served counters, with kernel gauges beside it
        served = sum(s["counters"].get("served_ntt", 0) for s in snaps)
        assert served == 1
        assert any("kernel_ntt_gflops" in s["gauges"] for s in snaps)
        assert all("index" in s and "uptime_s" in s for s in snaps)
        # labelled Prometheus rendering: one series per worker
        text = OF.render_prom(entries)
        assert 'dpt_fleet_up{worker="0"' in text
        assert 'dpt_fleet_up{worker="1"' in text
        assert "dpt_fleet_served_ntt_total{" in text
        # suspect-aware: a quarantined worker is REPORTED, never dialed
        d.tracker.mark_suspect(1)
        entries = d.fleet_metrics()
        assert entries[1]["suspect"] and not entries[1]["usable"]
        assert entries[1]["snapshot"] is None
        assert entries[0]["snapshot"] is not None
        text = OF.render_prom(entries)
        assert 'dpt_fleet_suspect{worker="1"' in text
        # aggregates fold into a shared registry
        from distributed_plonk_tpu.service.metrics import Metrics
        m = Metrics()
        OF.aggregate(entries, m)
        snap = m.snapshot()
        assert snap["gauges"]["fleet_width"] == 2
        assert snap["gauges"]["fleet_suspects"] == 1
        assert snap["counters"]["fleet_scrapes"] == 1
    finally:
        _shutdown(d)
        _kill_all(procs)


# --- wire back-compat --------------------------------------------------------

def _stub_old_worker():
    """A pre-ISSUE-15 worker: framed transport, answers PING/HEALTH,
    ERRs on everything else — exactly how an old daemon meets the new
    tags. Returns (host, port, closer)."""
    listener = native.Listener("127.0.0.1", 0)
    port = native.listener_port(listener)

    def serve_conn(conn):
        try:
            while True:
                try:
                    tag, _payload = conn.recv()
                except ConnectionError:
                    return
                tag &= ~protocol.TRACED
                if tag == protocol.PING:
                    conn.send(protocol.OK)
                elif tag == protocol.HEALTH:
                    conn.send(protocol.OK, json.dumps(
                        {"uptime_s": 1.0, "served": 0,
                         "now": time.time()}).encode())
                else:
                    conn.send(protocol.ERR, b"unknown tag")
        finally:
            conn.close()

    def accept_loop():
        while True:
            try:
                conn = listener.accept()
            except Exception:
                return
            if conn.fd < 0:
                return
            threading.Thread(target=serve_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    return "127.0.0.1", port, listener.close


def test_unknown_tags_degrade_and_never_kill_serving(tmp_path):
    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD

    cfg, procs = _spawn_workers(tmp_path, 1, 34200)
    sh, sp, close_stub = _stub_old_worker()
    mixed = NetworkConfig([f"{cfg.workers[0][0]}:{cfg.workers[0][1]}",
                           f"{sh}:{sp}"])
    d = Dispatcher(mixed)
    try:
        # new dispatcher vs OLD worker: every new tag degrades to an
        # empty/unsupported result — never an exception, never a breaker
        entries = d.fleet_metrics()
        assert entries[1]["reachable"] and entries[1].get("unsupported")
        assert entries[1]["snapshot"] is None
        assert entries[0]["snapshot"] is not None
        logs = d.fetch_logs(worker=1)
        assert logs == [{"worker": 1, "events": [], "seq": 0}]
        meta, blob = d.profile_worker(1)
        assert meta["format"] == "unsupported" and blob == b""
        assert d.tracker.usable(1)  # ERR replies are not failures
        # ...and serving still works: an NTT routed AT the old worker
        # rotates onto the new one and answers correctly
        values = [RNG.randrange(R_MOD) for _ in range(16)]
        assert d.ntt(values, worker=1) == P.fft(P.Domain(16), values)

        # the reverse: a NEW worker answers an unknown tag with ERR and
        # the connection keeps serving (an old dispatcher keeps working)
        h, p = cfg.workers[0]
        conn = native.connect(h, p)
        try:
            conn.send(99, b"")
            rtag, rbody = conn.recv()
            assert rtag == protocol.ERR and b"unknown tag" in rbody
            conn.send(protocol.NTT,
                      protocol.encode_ntt_request(values, False, False))
            rtag, rbody = conn.recv()
            assert rtag == protocol.OK
            assert protocol.decode_scalars(rbody) == \
                P.fft(P.Domain(16), values)
        finally:
            conn.close()
    finally:
        close_stub()
        _shutdown(d)
        _kill_all(procs)


# --- service plane: ObsServer endpoints over an attached fleet ---------------

def test_service_fleet_obs_endpoints(tmp_path):
    from distributed_plonk_tpu.service import ProofService
    from distributed_plonk_tpu.service.server import ObsServer

    olog.reset()
    cfg, procs = _spawn_workers(tmp_path, 2, 34900)
    d = Dispatcher(cfg)
    svc = ProofService(port=0, prover_workers=1,
                       store_dir=str(tmp_path / "store"),
                       backend_factory=lambda: RemoteBackend(
                           d, dist_fft_min=64)).start()
    svc.attach_fleet(d, interval_s=0.3)
    obs = ObsServer(svc).start()
    base = f"http://{obs.host}:{obs.port}"
    try:
        job = svc.submit_local({"kind": "toy", "gates": 16, "seed": 5})
        assert job.done_event.wait(timeout=180) and job.state == "done"
        svc.fleet.scrape_once()  # deterministic: don't race the interval

        # /metrics: service exposition + labelled per-worker series
        text = _get(base + "/metrics").decode()
        assert "dpt_jobs_completed_total 1" in text
        assert 'dpt_fleet_up{worker="0"' in text
        assert 'dpt_fleet_up{worker="1"' in text
        assert "dpt_fleet_served_msm_total{" in text
        assert "dpt_fleet_width 2" in text

        # /healthz: LB truth now carries the fleet summary
        h = json.loads(_get(base + "/healthz"))
        assert h["ok"] is True
        assert h["fleet"] == {"epoch": 0, "width": 2, "usable": 2,
                              "suspects": 0, "breakers_open": 0}

        # /fleet: every member named with breaker/suspect state
        fl = json.loads(_get(base + "/fleet"))
        assert fl["width"] == 2 and len(fl["members"]) == 2
        for m in fl["members"]:
            assert {"index", "addr", "usable", "suspect", "left",
                    "reachable", "snapshot"} <= set(m)
            assert m["reachable"] and m["snapshot"]

        # /logs: the service process's ring over HTTP
        lg = json.loads(_get(base + "/logs?limit=50"))
        assert "events" in lg and "seq" in lg

        # /profile/capture -> /profile/<id>: on-demand capture stored as
        # a content-addressed artifact and served back
        cap = json.loads(_get(base + "/profile/capture?worker=0&ms=60"))
        assert cap["profile_id"] and cap["format"] == "pystacks-json"
        blob = _get(base + "/profile/" + cap["profile_id"])
        prof = json.loads(blob)
        assert prof["format"] == "pystacks-json" and prof["samples"] >= 1
        from distributed_plonk_tpu.store import keycache as KC
        assert svc.store.get_entry(
            KC.profile_store_key(cap["profile_id"])) is not None
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/profile/deadbeef00000000")
        assert ei.value.code == 404

        # the operator console renders one pane from these endpoints
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "console.py"),
             "--obs", f"{obs.host}:{obs.port}", "--once", "--logs", "5"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "fleet    epoch=0 width=2" in out.stdout
        assert "[ 0]" in out.stdout and "[ 1]" in out.stdout
    finally:
        obs.close()
        svc.shutdown()
        _shutdown(d)
        _kill_all(procs)


# --- THE acceptance criterion: one pane over a supervised fleet prove --------

def test_supervised_fleet_prove_one_pane(tmp_path):
    """Live 3-worker supervised fleet prove with a mid-FFT1 worker kill:
    one ObsServer yields the aggregated per-worker series, the /fleet
    snapshot, a merged trace:<job_id> artifact whose structured logs
    carry dispatcher AND supervisor AND worker events under the prove's
    trace id, and a fetchable profile:<id> — proof bytes byte-identical
    to the host oracle."""
    import random as _random
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.proof_io import serialize_proof
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
    from distributed_plonk_tpu.service import ProofService
    from distributed_plonk_tpu.service.jobs import (JobSpec, build_circuit,
                                                    build_bucket_keys)
    from distributed_plonk_tpu.service.metrics import Metrics
    from distributed_plonk_tpu.service.server import ObsServer

    olog.reset()
    spec_obj = {"kind": "toy", "gates": 16, "seed": 7}
    spec = JobSpec.from_wire(spec_obj)
    ckt = build_circuit(spec)
    pk = build_bucket_keys(spec)[1]
    want = serialize_proof(prove(_random.Random(spec.seed), ckt, pk,
                                 PythonBackend()))

    metrics = Metrics()
    faults = FaultInjector(
        [Rule("kill", tag=protocol.FFT1, worker=1, nth=1, plane="proc")],
        metrics=metrics)
    d = Dispatcher(NetworkConfig([]), metrics=metrics, faults=faults,
                   tracer=Tracer(proc="dispatcher"))
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    mserver = d.enable_membership()
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=3,
                           backend="python", metrics=metrics, cwd=REPO,
                           probe_interval_s=0.1, backoff_base_s=0.05,
                           backoff_max_s=0.5).start()
    faults.proc_kill_cb = sup.proc_killer(d)
    svc = ProofService(port=0, prover_workers=1, max_retries=4,
                       store_dir=str(tmp_path / "store"),
                       backend_factory=lambda: RemoteBackend(
                           d, dist_fft_min=ckt.n)).start()
    svc.attach_fleet(d, interval_s=0.5)
    obs = ObsServer(svc).start()
    base = f"http://{obs.host}:{obs.port}"
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            if len(d.workers) == 3 and len(d.tracker.usable_set()) == 3:
                break
            time.sleep(0.1)
        assert len(d.tracker.usable_set()) == 3, "fleet never came up"
        for w in d.workers:
            w.RECONNECT_TRIES = 2
            w.BACKOFF_BASE_S = 0.01
            w.BACKOFF_MAX_S = 0.05

        job = svc.submit_local(spec_obj)
        assert job.done_event.wait(timeout=240) and job.state == "done", \
            (job.state, job.error)
        assert job.proof_bytes == want  # byte-identical through the kill
        assert metrics.snapshot()["counters"].get(
            "faults_injected_kill", 0) == 1

        # wait for the heal (respawn + rejoin) so the supervisor's log
        # events exist before the timeline is collected
        deadline = time.time() + 120
        while time.time() < deadline:
            ctr = metrics.snapshot()["counters"]
            if ctr.get("worker_respawns", 0) >= 1 \
                    and len(d.tracker.usable_set()) == 3:
                break
            time.sleep(0.1)
        assert metrics.snapshot()["counters"].get(
            "worker_respawns", 0) >= 1

        # ONE artifact: service spans + fleet spans + structured logs
        merged = svc.merge_fleet_trace(job.id)
        assert merged["trace_id"] == job.trace_id
        subsystems = {e["subsystem"] for e in merged["logs"]}
        assert {"dispatcher", "supervisor", "worker"} <= subsystems, \
            subsystems
        assert all(e.get("trace_id") == job.trace_id
                   for e in merged["logs"])
        # the incident reads off the artifact: the replan the kill forced
        assert any(e["subsystem"] == "dispatcher"
                   and e["event"] in ("fft_replan", "fft_degraded",
                                      "range_adopted")
                   for e in merged["logs"])
        assert any(e["subsystem"] == "supervisor"
                   and e["event"] == "respawn" for e in merged["logs"])
        # worker spans made it into the same timeline
        procs_ = {e.get("proc") for e in merged["events"]}
        assert any(str(p).startswith("worker/") for p in procs_), procs_

        # ...and it is served at /trace/<job_id> (raw + chrome forms)
        raw = json.loads(_get(base + f"/trace/{job.id}?raw=1"))
        assert raw["trace_id"] == job.trace_id
        assert {e["subsystem"] for e in raw["logs"]} >= \
            {"dispatcher", "supervisor", "worker"}
        ct = json.loads(_get(base + f"/trace/{job.id}"))
        instants = [e for e in ct["traceEvents"] if e.get("ph") == "i"]
        assert any(e["name"] == "supervisor/respawn" for e in instants)

        # aggregated per-worker series + fleet snapshot from the SAME
        # ObsServer
        svc.fleet.scrape_once()
        text = _get(base + "/metrics").decode()
        for i in range(3):
            assert f'dpt_fleet_up{{worker="{i}"' in text
        assert "dpt_fleet_width 3" in text
        fl = json.loads(_get(base + "/fleet"))
        assert fl["width"] == 3 and fl["epoch"] >= 4  # 3 joins + rejoin
        assert all("suspect" in m and "usable" in m
                   for m in fl["members"])
        h = json.loads(_get(base + "/healthz"))
        assert h["fleet"]["width"] == 3 and h["fleet"]["epoch"] == \
            fl["epoch"]

        # a fetchable on-demand profile artifact, linked from the plane
        cap = json.loads(_get(base + "/profile/capture?worker=0&ms=60"))
        assert cap["profile_id"]
        assert _get(base + "/profile/" + cap["profile_id"])
    finally:
        obs.close()
        svc.shutdown()
        sup.stop()
        d.shutdown()
        d.pool.shutdown(wait=False)


# --- serve.py daemon: --log-dir sink + enriched healthz ----------------------

def test_serve_subprocess_log_dir_and_shed_event(tmp_path):
    from distributed_plonk_tpu.service import ServiceClient

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DPT_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(SCRIPTS, "serve.py"),
         "--port", "0", "--obs-port", "0", "--workers", "1",
         "--log-dir", str(tmp_path / "logs"),
         "--allow-remote-shutdown"],
        stdout=subprocess.PIPE, env=env, text=True, cwd=REPO)
    try:
        banner = json.loads(proc.stdout.readline())
        assert banner["log_file"] and os.path.exists(banner["log_file"])
        host, port = banner["listening"].rsplit(":", 1)
        base = f"http://{banner['obs']}"
        with ServiceClient(host, int(port)) as c:
            # a ttl that lapses before the prove starts: shed verdict ->
            # a structured log event in the ring (served at /logs) AND
            # the JSONL file sink
            r = c.submit({"kind": "toy", "gates": 16, "seed": 3,
                          "ttl_s": 1e-6})
            deadline = time.time() + 60
            while time.time() < deadline:
                st = c.status(r["job_id"])
                if st["state"] in ("shed", "done", "failed"):
                    break
                time.sleep(0.1)
            assert st["state"] == "shed", st
            lg = json.loads(_get(base + "/logs"))
            shed = [e for e in lg["events"] if e["event"] == "shed"]
            assert shed and shed[0]["subsystem"] == "service"
            assert shed[0]["job_id"] == r["job_id"]
            # healthz without a fleet: explicit null, not a lie
            h = json.loads(_get(base + "/healthz"))
            assert h["fleet"] is None
            c.shutdown_server()
        proc.wait(timeout=30)
        lines = [json.loads(line) for line in open(banner["log_file"])]
        assert any(e["event"] == "shed" for e in lines)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)


# --- perf-regression gate ----------------------------------------------------

def _bench_record():
    sys.path.insert(0, SCRIPTS)
    import bench_record
    return bench_record


def test_bench_record_normalize_and_compare(tmp_path):
    BR = _bench_record()
    line = {"metric": "prove_2p13_wall_clock", "value": 3.8, "unit": "s",
            "proofs_per_s": 1.4, "analysis_clean": True,
            "fleet_heal_s": 2.3, "degraded_reason": "nope",
            "ntt_stage_breakdown": {"radix4_stage_s": 0.01},
            "baseline_basis": "prose is dropped"}
    rec = BR.normalize("bench", line, run=9)
    assert rec["schema"] == BR.SCHEMA and rec["basis"] == "chip"
    assert rec["keys"]["headline/prove_2p13_wall_clock"] == 3.8
    assert rec["keys"]["ntt_stage_breakdown/radix4_stage_s"] == 0.01
    assert "baseline_basis" not in rec["keys"]  # strings dropped
    assert BR.normalize("bench", dict(line, degraded=True))["basis"] == \
        "degraded"

    # direction + tolerance: a 60% proofs_per_s drop fails, 20% passes,
    # heal time may grow inside tolerance, booleans flipping false fail
    prev = BR.normalize("bench", line)
    worse = BR.normalize("bench", dict(line, proofs_per_s=0.5))
    regs = BR.compare(prev, worse)
    assert [r["key"] for r in regs] == ["proofs_per_s"]
    ok = BR.normalize("bench", dict(line, proofs_per_s=1.2,
                                    fleet_heal_s=4.0))
    assert BR.compare(prev, ok) == []
    flipped = BR.normalize("bench", dict(line, analysis_clean=False))
    assert any(r["key"] == "analysis_clean" and r["change"] ==
               "flipped false" for r in BR.compare(prev, flipped))
    # unwatched / new keys never gate
    novel = BR.normalize("bench", dict(line, brand_new_number=1))
    assert BR.compare(prev, novel) == []

    # trajectory append/load round trip + basis-aware pairing
    repo = str(tmp_path)
    assert BR.append(prev, repo=repo)
    assert BR.append(BR.normalize("bench", dict(line, degraded=True)),
                     repo=repo)
    records = BR.load_trajectory(repo)
    assert [r["basis"] for r in records] == ["chip", "degraded"]
    assert BR.latest_of_basis(records, "chip") is records[0]


def test_bench_compare_committed_trajectory_green():
    """The ci.sh benchcheck contract: the committed perf history (legacy
    BENCH_r*.json + trajectory.jsonl) gates green, loudly and
    non-flakily (no measurement runs)."""
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_compare.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, (out.stdout, out.stderr)
    verdict = json.loads(out.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True and verdict["regressions"] == []
    assert verdict["records"] >= 4  # the legacy files normalized too
    # and a regressing line IS caught (the gate has teeth)
    bad = json.dumps({"metric": "prove_2p13_wall_clock", "value": None,
                      "unit": "s", "degraded": True,
                      "cpu_ntt_2p14_elements_per_s": 1})
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_compare.py"),
         "--line", bad],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 1
    assert "REGRESSION" in out.stderr
