"""Driver-hook tests: entry() compiles, dryrun_multichip(8) fits the budget.

Round-1 regression guard: MULTICHIP_r01.json was rc=124 because the mesh
MSM program took >8 min of XLA compile on the virtual CPU mesh; nothing in
tests/ exercised the dryrun itself. This runs it exactly the way the
driver does (subprocess, fresh interpreter, forced CPU platform) under an
explicit wall-clock budget.
"""

import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent

# generous vs the ~2 min measured cold; catches a regression back toward
# the round-1 ~9 min state while tolerating shared-host noise
BUDGET_S = 480


def test_dryrun_multichip_8_within_budget():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # force the plain CPU platform
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN_OK')"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=BUDGET_S)
    elapsed = time.time() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout
    assert elapsed < BUDGET_S


def test_entry_compiles_and_runs():
    import numpy as np
    import __graft_entry__ as g

    fn, args = g.entry()
    out = fn(*args)
    assert np.asarray(out).shape == np.asarray(args[0]).shape
