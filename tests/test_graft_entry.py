"""Driver-hook tests: entry() compiles, dryrun_multichip(8) fits the budget.

Round-1 regression: MULTICHIP_r01.json was rc=124 because the mesh MSM
program took >8 min of XLA compile on the virtual CPU mesh. Round-2
regression: MULTICHIP_r02.json was rc=124 again because the dryrun was run
with the driver's live env (JAX_PLATFORMS=axon + PALLAS_AXON_POOL_IPS) while
the relay was dead — platform discovery blocks forever. The round-2 version
of this test quietly scrubbed that env, masking exactly the failure mode it
existed to catch. These tests now cover BOTH environments: the clean CPU env
and a hostile env simulating a dead relay (pool IP pointing at a
non-routable blackhole address), which dryrun_multichip must survive by
re-executing its body in a scrubbed subprocess.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

# matches the driver-facing _DRYRUN_TIMEOUT_S contract: since round 4 the
# dryrun runs a FULL tiny mesh prove (cold-compiles the SPMD prover
# programs, ~15-20 min cold on a shared 8-core host; minutes warm via the
# persistent compile cache)
BUDGET_S = 2400

# TEST-NET-1 address (RFC 5737): guaranteed non-routable, so a connect
# attempt hangs/black-holes — the observed behavior of the dead relay
DEAD_RELAY_ENV = {
    "PALLAS_AXON_POOL_IPS": "192.0.2.1",
    "JAX_PLATFORMS": "axon",
    "PALLAS_AXON_REMOTE_COMPILE": "1",
    "PALLAS_AXON_TPU_GEN": "v5e",
}


def _run_dryrun(env):
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN_OK')"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=BUDGET_S)
    return proc, time.time() - t0


@pytest.mark.slow
def test_dryrun_multichip_8_within_budget():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # force the plain CPU platform
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc, elapsed = _run_dryrun(env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout
    assert elapsed < BUDGET_S


@pytest.mark.slow
def test_dryrun_multichip_8_survives_dead_relay():
    """The driver's actual failure condition: axon env present, relay dead."""
    env = dict(os.environ)
    env.update(DEAD_RELAY_ENV)
    env.pop("XLA_FLAGS", None)  # the driver sets it; the dryrun must not rely on it
    proc, elapsed = _run_dryrun(env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout
    assert elapsed < BUDGET_S


@pytest.mark.slow
def test_bench_emits_valid_json_with_dead_relay():
    """bench.py must print one valid JSON line at rc=0 even when the TPU is
    unreachable (round-2 failure: BENCH_r02.json was rc=1, parsed:null)."""
    env = dict(os.environ)
    env.update(DEAD_RELAY_ENV)
    env["DPT_BENCH_PROBE_TIMEOUT"] = "20"   # keep the dead-probe phase short
    env["DPT_BENCH_TIMEOUT"] = "60"
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, "bench printed nothing"
    out = json.loads(lines[-1])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, out
    assert out.get("degraded") is True
    # degraded mode must NOT present a stale recorded number as this
    # run's value (round-3 advisor finding): value is null and the
    # recorded chip measurement moves to its own clearly-marked key
    assert out["value"] is None
    assert isinstance(out["recorded_prove_2p13_s"], (int, float))


def test_entry_compiles_and_runs():
    import numpy as np
    import __graft_entry__ as g

    fn, args = g.entry()
    out = fn(*args)
    assert np.asarray(out).shape == np.asarray(args[0]).shape
