"""Device prover-kernel tests: bit-identical to the host oracle."""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_plonk_tpu.constants import R_MOD, FR_GENERATOR
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.fields import fr_inv, batch_inverse
from distributed_plonk_tpu.backend import prover_jax as PJ

rng = random.Random(11)


def rand_vals(n):
    return [rng.randrange(R_MOD) for _ in range(n)]


def test_lift_lower_roundtrip():
    vals = rand_vals(17)
    assert PJ.lower(jnp.asarray(PJ.lift(vals))) == vals


def test_cumprod_matches_host():
    vals = rand_vals(33)
    got = PJ.lower(jax.jit(PJ.cumprod)(jnp.asarray(PJ.lift(vals))))
    acc, want = 1, []
    for v in vals:
        acc = acc * v % R_MOD
        want.append(acc)
    assert got == want


def test_fr_pow_matches_host():
    vals = rand_vals(5)
    for e in (1, 2, 5, R_MOD - 2, 1 << 20):
        got = PJ.lower(jax.jit(PJ.fr_pow, static_argnums=1)(jnp.asarray(PJ.lift(vals)), e))
        assert got == [pow(v, e, R_MOD) for v in vals], e


def test_batch_inverse_matches_host():
    vals = rand_vals(50)
    got = PJ.lower(jax.jit(PJ.batch_inverse)(jnp.asarray(PJ.lift(vals))))
    assert got == batch_inverse(vals, R_MOD)


def test_poly_eval_matches_host():
    for n in (1, 7, 300, 1030):
        poly = rand_vals(n)
        z = rng.randrange(R_MOD)
        zc = jnp.asarray(PJ.lift_scalar(z))
        got = PJ.lower(PJ.poly_eval_jit(jnp.asarray(PJ.lift(poly)), zc))
        assert got == [P.poly_eval(poly, z)], n


def test_synthetic_divide_matches_host():
    for n in (2, 9, 257):
        poly = rand_vals(n)
        z = rng.randrange(1, R_MOD)
        zc = jnp.asarray(PJ.lift_scalar(z))
        got = PJ.lower(PJ.synthetic_divide_jit(jnp.asarray(PJ.lift(poly)), zc))
        assert got == P.synthetic_divide(poly, z), n


def test_lin_comb_matches_host():
    polys = [rand_vals(5), rand_vals(9), rand_vals(3)]
    coeffs = rand_vals(3)
    L = max(len(p) for p in polys)
    stacked = jnp.stack([jnp.pad(jnp.asarray(PJ.lift(p)), ((0, 0), (0, L - len(p))))
                         for p in polys], axis=1)
    cf = jnp.asarray(PJ.lift(coeffs)).reshape(16, len(coeffs), 1)
    got = PJ.lower(PJ.lin_comb_jit(stacked, cf))
    want = []
    for p, c in zip(polys, coeffs):
        want = P.poly_add(want, P.poly_scale(p, c))
    want += [0] * (9 - len(want))
    assert got == want


def test_add_vanishing_blind_matches_host():
    n = 16
    coeffs = rand_vals(n)
    blinds = rand_vals(3)
    got = PJ.lower(PJ.blind_jit(jnp.asarray(PJ.lift(coeffs)),
                                jnp.asarray(PJ.lift(blinds)), n))
    want = P.poly_add(P.poly_mul_vanishing(blinds, n), coeffs)
    assert got == want


def test_tail_is_zero():
    poly = rand_vals(6) + [0, 0]
    h = jnp.asarray(PJ.lift(poly))
    assert PJ.tail_is_zero(h, 5)
    assert not PJ.tail_is_zero(h, 4)


def test_domain_tables_match_host():
    n, m = 8, 32
    dom = P.Domain(m)
    g = FR_GENERATOR
    tabs = PJ.domain_tables_jit(m, n, g, dom.group_gen)
    ep = PJ.lower(tabs["ep"])
    want_ep = []
    cur = g
    for _ in range(m):
        want_ep.append(cur)
        cur = cur * dom.group_gen % R_MOD
    assert ep == want_ep
    ratio = m // n
    zh_inv = PJ.lower(tabs["zh_inv"])
    assert zh_inv == [fr_inv((pow(want_ep[i % ratio], n, R_MOD) - 1) % R_MOD)
                      for i in range(m)]
    shifted_inv = PJ.lower(tabs["shifted_inv"])
    assert shifted_inv == [fr_inv((e - 1) % R_MOD) for e in want_ep]
