"""Field-arithmetic oracle tests (constants, tower laws, batch inverse)."""

import random

from distributed_plonk_tpu import fields as F
from distributed_plonk_tpu.constants import (
    R_MOD,
    Q_MOD,
    BLS_Z,
    FR_ROOT_OF_UNITY,
    FR_TWO_ADICITY,
    FR_MONT_R,
    FR_MONT_INV,
    FQ_MONT_R,
    FQ_MONT_INV,
)

rng = random.Random(0xF1E1D)


def test_moduli_match_bls_parameterisation():
    assert R_MOD == BLS_Z ** 4 - BLS_Z ** 2 + 1
    assert Q_MOD == (BLS_Z - 1) ** 2 * R_MOD // 3 + BLS_Z
    assert R_MOD.bit_length() == 255
    assert Q_MOD.bit_length() == 381


def test_root_of_unity():
    assert pow(FR_ROOT_OF_UNITY, 1 << FR_TWO_ADICITY, R_MOD) == 1
    assert pow(FR_ROOT_OF_UNITY, 1 << (FR_TWO_ADICITY - 1), R_MOD) != 1
    w8 = F.fr_root_of_unity(8)
    assert pow(w8, 8, R_MOD) == 1 and pow(w8, 4, R_MOD) != 1


def test_montgomery_constants():
    assert FR_MONT_R == (1 << 256) % R_MOD
    assert (R_MOD * FR_MONT_INV) % (1 << 256) == (1 << 256) - 1
    assert (Q_MOD * FQ_MONT_INV) % (1 << 384) == (1 << 384) - 1
    assert FQ_MONT_R == (1 << 384) % Q_MOD


def test_fr_field_laws():
    for _ in range(100):
        a, b, c = (rng.randrange(R_MOD) for _ in range(3))
        assert F.fr_mul(F.fr_mul(a, b), c) == F.fr_mul(a, F.fr_mul(b, c))
        assert F.fr_mul(a, F.fr_add(b, c)) == F.fr_add(F.fr_mul(a, b), F.fr_mul(a, c))
        if a != 0:
            assert F.fr_mul(a, F.fr_inv(a)) == 1


def test_batch_inverse():
    vals = [rng.randrange(1, R_MOD) for _ in range(257)]
    invs = F.batch_inverse(vals, R_MOD)
    for v, i in zip(vals, invs):
        assert v * i % R_MOD == 1


def test_fq12_tower():
    def rfq2():
        return (rng.randrange(Q_MOD), rng.randrange(Q_MOD))

    def rfq12():
        return (
            (rfq2(), rfq2(), rfq2()),
            (rfq2(), rfq2(), rfq2()),
        )

    for _ in range(10):
        a, b, c = rfq12(), rfq12(), rfq12()
        assert F.fq12_mul(F.fq12_mul(a, b), c) == F.fq12_mul(a, F.fq12_mul(b, c))
        assert F.fq12_mul(a, F.fq12_inv(a)) == F.FQ12_ONE
        assert F.fq12_sq(a) == F.fq12_mul(a, a)
