"""Both bucket-plane update strategies (one-hot / put) must agree with
the host oracle bit-for-bit, and the group-width knob must sanitize its
input (the TPU default is onehot — the round-4 4.4x MSM fix — while CPU
tests otherwise only exercise put; this locks the other path in CI)."""

import random

import jax
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import msm_jax as M

RNG = random.Random(0x1407)


@pytest.mark.parametrize("mode,pack", [
    ("put", True), ("onehot", True), ("onehot", False)])
def test_update_strategies_match_oracle(mode, pack, monkeypatch):
    monkeypatch.setattr(M, "_BUCKET_UPDATE", mode)
    monkeypatch.setattr(M, "_PLANE_PACK", pack)
    # the strategy branch is resolved at trace time inside jitted scans:
    # drop cached executables so the patched mode actually traces
    jax.clear_caches()
    n = 256
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
           for _ in range(32)] * (n // 32)
    ks = [RNG.randrange(R_MOD) for _ in range(n)]
    try:
        assert M.msm(pts, ks) == C.g1_msm(pts, ks)
    finally:
        jax.clear_caches()


def test_group_max_knob_sanitized(monkeypatch):
    monkeypatch.setenv("DPT_MSM_GROUP_MAX", "768")  # non-power-of-two
    assert M._group_size(1 << 20) == 512  # rounded down, not collapsed to 1
    monkeypatch.setenv("DPT_MSM_GROUP_MAX", "0")
    assert M._group_size(1 << 20) >= 1
    monkeypatch.setenv("DPT_MSM_GROUP_MAX", "2048")
    # the g*1024 > n fold-work cap still applies above the default
    assert M._group_size(1 << 20) == 1024
    assert M._group_size(1 << 10) == 1