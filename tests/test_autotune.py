"""Kernel autotuner + calibration artifacts (ISSUE 14).

The acceptance surface of the measured kernel-dispatch plan:

  - a plan round-trips through the content-addressed store byte for byte
  - a fingerprint mismatch (foreign/hand-copied plan) means REBUILD,
    never crash and never another machine's winners
  - the winner parity gate rejects a fast-but-WRONG candidate (injected
    via a lying fake timer)
  - an explicit DPT_* knob beats the plan at every resolver
  - DPT_AUTOTUNE=off (and a plan-less load) is byte- and counter-
    identical to the pre-autotune tree
  - ProofService and a fleet worker pick a store plan up at startup with
    zero measurement runs, and a mid-process plan reload can never serve
    a kernel memo entry traced under the previous plan (cache_key folds
    the plan revision into every memo key)

Everything runs at tiny shapes on XLA:CPU (the `ci.sh autotune` smoke
tier, which `ci.sh fast` includes).
"""

import threading

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_plonk_tpu.backend import autotune as AT
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend import msm_jax as MJ
from distributed_plonk_tpu.backend import ntt_jax as NJ
from distributed_plonk_tpu.constants import FR_LIMBS, FR_MONT_R, R_MOD
from distributed_plonk_tpu.backend.limbs import ints_to_limbs
from distributed_plonk_tpu.service.metrics import Metrics
from distributed_plonk_tpu.store import ArtifactStore, calibration

N = 64  # tiny calibration shape: every kernel compiles in seconds on CPU


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    """Every test starts knob-free and plan-free, and leaves the
    process-global plan the way it found it."""
    for k in ("DPT_AUTOTUNE", "DPT_NTT_RADIX", "DPT_NTT_KERNEL",
              "DPT_MSM_GROUP_MAX", "DPT_FIELD_MUL", "DPT_MSM_C"):
        monkeypatch.delenv(k, raising=False)
    prev = AT.active_plan()
    AT.set_active_plan(None)
    yield
    AT.set_active_plan(prev)


def _mont_vec(n, seed=7):
    rng = np.random.default_rng(seed)
    vals = rng.integers(1, 1 << 62, size=n, dtype=np.int64)
    return jnp.asarray(ints_to_limbs(
        [int(v) * FR_MONT_R % R_MOD for v in vals], FR_LIMBS))


def _plan_for_here(cells):
    return AT.KernelPlan(AT.machine_fingerprint(), cells)


# --- plan artifact mechanics -------------------------------------------------

def test_parse_shapes():
    assert calibration.parse_shapes("2^10, 2^14,4096") == [1024, 4096, 16384]


def test_plan_store_roundtrip_byte_identical(tmp_path):
    store = ArtifactStore(str(tmp_path))
    plan = _plan_for_here({("ntt", N): {"params": {"radix": 2,
                                                   "kernel": "xla"}},
                           ("field", N): {"params": {"mul": "f32"}}})
    plan.meta = {"budget_s": 1.0}
    digest1 = calibration.store_plan(store, plan)
    blob = store.get(calibration.plan_store_key(plan.fingerprint))
    assert blob == plan.to_json_bytes()
    back = calibration.load_plan(store)
    assert back is not None
    assert back.to_json_bytes() == plan.to_json_bytes()
    assert back.cells == plan.cells and back.meta == plan.meta
    # canonical JSON: re-storing the identical plan is the identical blob
    assert calibration.store_plan(store, back) == digest1


def test_foreign_fingerprint_means_rebuild_not_crash(tmp_path, monkeypatch):
    store = ArtifactStore(str(tmp_path))
    fp = AT.machine_fingerprint()
    # a hand-copied artifact: OUR key, ANOTHER machine's embedded id
    foreign = AT.KernelPlan("feedfacef00d",
                            {("ntt", N): {"params": {"radix": 2}}})
    store.put(calibration.plan_store_key(fp), foreign.to_json_bytes())
    assert calibration.load_plan(store) is None

    calls = []

    class FakeTuner:
        def __init__(self, shapes, budget_s=None, metrics=None, **kw):
            calls.append(shapes)

        def run(self, aot=False):
            return _plan_for_here({("ntt", N): {"params": {"radix": 4}}})

    monkeypatch.setattr(AT, "Autotuner", FakeTuner)
    rep = calibration.load_or_run(store, mode="run", shapes=[N], aot=False)
    assert rep["source"] == "fresh" and calls == [[N]]
    assert AT.active_plan().fingerprint == fp
    # the rebuilt plan replaced the foreign blob under the same key
    assert calibration.load_plan(store).lookup("ntt", "radix") == 4


def test_future_plan_version_is_ignored(tmp_path):
    store = ArtifactStore(str(tmp_path))
    plan = _plan_for_here({})
    blob = plan.to_json_bytes().replace(b'"version": 1',
                                        b'"version": 999')
    store.put(calibration.plan_store_key(plan.fingerprint), blob)
    assert calibration.load_plan(store) is None
    assert AT.KernelPlan.from_json_bytes(b"not json at all") is None


def test_calibration_lock_measures_once(tmp_path, monkeypatch):
    """Concurrent starters against one store: one measures under the
    fcntl lock, the loser loads the winner's plan."""
    store = ArtifactStore(str(tmp_path))
    runs = []

    class SlowTuner:
        def __init__(self, shapes, budget_s=None, metrics=None, **kw):
            pass

        def run(self, aot=False):
            runs.append(1)
            return _plan_for_here({("ntt", N): {"params": {"radix": 2}}})

    monkeypatch.setattr(AT, "Autotuner", SlowTuner)
    reports = []
    threads = [threading.Thread(target=lambda: reports.append(
        calibration.load_or_run(store, mode="run", shapes=[N], aot=False)))
        for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(runs) == 1
    assert sorted(r["source"] for r in reports) == ["fresh", "store",
                                                    "store"]


# --- precedence: env knob > plan > default -----------------------------------

def test_plan_drives_resolvers_and_env_overrides(monkeypatch):
    AT.set_active_plan(_plan_for_here({
        ("ntt", N): {"params": {"radix": 2, "kernel": "xla"}},
        ("msm", N): {"params": {"bucket_update": "onehot",
                                "group_max": 1024, "c": 8}},
        ("field", N): {"params": {"mul": "u32"}},
    }))
    # plan wins over built-in defaults (radix 4 / put-on-cpu / 512 / 7)
    assert NJ._active_radix(n=N) == 2
    assert MJ._use_onehot_update(N) is True
    assert MJ._group_max_knob(N) == 1024
    assert MJ._c_batch_knob(1 << 10) == 8
    assert FJ._mul_path(N) == "u32" and FJ._f32_active(N) is False
    # nearest-cell lookup: a nearby size resolves to the calibrated cell
    assert NJ._active_radix(n=2 * N) == 2
    # explicit env knobs win over the plan at every resolver
    monkeypatch.setenv("DPT_NTT_RADIX", "4")
    monkeypatch.setenv("DPT_MSM_GROUP_MAX", "256")
    assert NJ._active_radix(n=N) == 4
    assert MJ._group_max_knob(N) == 256
    # attr-latched knobs: a test/registry patch away from the default
    # counts as explicit too
    monkeypatch.setattr(MJ, "_BUCKET_UPDATE", "put")
    monkeypatch.setattr(FJ, "_MUL_MODE", "f32")
    assert MJ._use_onehot_update(N) is False
    assert FJ._mul_path(N) == "f32" and FJ._f32_active(N) is True
    monkeypatch.setenv("DPT_MSM_C", "7")
    monkeypatch.setattr(MJ.MsmContext, "_C_BATCH", 7)
    assert MJ._c_batch_knob(1 << 10) == 7


def test_malformed_plan_values_fall_back_to_defaults():
    """A plan is machine state, not operator input: values outside the
    accepted choices (or non-numeric garbage) resolve to the built-in
    defaults instead of raising at dispatch time — a broken plan must
    never break a prove (only explicit knobs may raise)."""
    from distributed_plonk_tpu.backend import field_pallas as FP

    AT.set_active_plan(_plan_for_here(
        {("msm", 1 << 10): {"params": {"c": 9, "group_max": "junk"}},
         ("ntt", 1 << 10): {"params": {"radix": 3}},
         ("field", 1 << 10): {"params": {"lane_tile": 0}}}))
    assert MJ._c_batch_knob(1 << 10) == 7
    assert MJ._group_max_knob(1 << 10) == 512
    assert NJ._active_radix(n=1 << 10) == 4
    # lane_tile divides the padded lane count: 0/non-power-of-two plan
    # values must never reach the BlockSpec math
    assert FP.lane_tile(1 << 10) == FP.LANE_TILE_DEFAULT


# --- off / plan-less parity --------------------------------------------------

def test_off_mode_touches_nothing(tmp_path):
    store = ArtifactStore(str(tmp_path))
    calibration.store_plan(
        store, _plan_for_here({("ntt", N): {"params": {"radix": 2}}}))
    v = _mont_vec(N)
    before = np.asarray(NJ.get_plan(N).kernel(boundary="mont")(v)).tobytes()
    m = Metrics()
    rep = calibration.load_or_run(store, mode="off", metrics=m)
    assert rep == {"source": "off"}
    assert AT.active_plan() is None  # the stored plan was not even read
    assert m.snapshot()["counters"] == {}
    after = np.asarray(NJ.get_plan(N).kernel(boundary="mont")(v)).tobytes()
    assert after == before


def test_plan_less_load_is_counter_free(tmp_path):
    m = Metrics()
    rep = calibration.load_or_run(ArtifactStore(str(tmp_path)), mode="load",
                                  metrics=m)
    assert rep["source"] == "none" and rep["measure_runs"] == 0
    assert AT.active_plan() is None
    assert m.snapshot()["counters"] == {}
    with pytest.raises(ValueError):
        calibration.load_or_run(ArtifactStore(str(tmp_path)), mode="bogus")


# --- the measure pass --------------------------------------------------------

def test_parity_gate_rejects_lying_candidate():
    """A candidate that returns WRONG bytes with a too-good-to-be-true
    timer must lose to the (slower) parity core, and be counted."""

    class LyingTuner(AT.Autotuner):
        def _run_candidate(self, kind, n, cand):
            out, dt, aux = super()._run_candidate(kind, n, cand)
            if cand.get("radix") == 4:  # the non-parity candidate lies
                return b"fast wrong answer", 1e-9, aux
            return out, dt, aux

    m = Metrics()
    plan = LyingTuner([N], budget_s=600, kinds=("ntt",), metrics=m).run()
    cell = plan.cell("ntt", N)
    assert cell is not None
    assert cell["params"]["radix"] == 2  # the liar was NOT adopted
    assert cell["parity_rejects"] >= 1
    assert m.snapshot()["counters"]["autotune_parity_rejects"] >= 1


def test_cell_abandoned_when_parity_core_fails():
    """If the PARITY CORE itself cannot be measured, the cell is dropped
    (defaults stay in force) — the next candidate must never silently
    become the bit-identity reference."""

    class BrokenParityTuner(AT.Autotuner):
        def _run_candidate(self, kind, n, cand):
            if cand == self.PARITY[kind]:
                raise RuntimeError("parity core refused to run")
            return super()._run_candidate(kind, n, cand)

    m = Metrics()
    plan = BrokenParityTuner([N], budget_s=600, kinds=("ntt",),
                             metrics=m).run()
    assert plan.cell("ntt", N) is None
    assert m.snapshot()["counters"]["autotune_candidate_errors"] >= 1
    assert "autotune_parity_rejects" not in m.snapshot()["counters"]


def test_cell_dropped_when_budget_stops_before_default():
    """A budget that expires after the parity reference but before the
    knob-free default config was measured leaves the cell UNDECIDED: it
    must be dropped, not persisted with the (slow) parity core as its
    winner — a truncated run stays 'always safe' (defaults in force)."""

    class OneMeasureTuner(AT.Autotuner):
        def _run_candidate(self, kind, n, cand):
            out = super()._run_candidate(kind, n, cand)
            self._deadline = 0.0  # budget gone after the first measure
            return out

    plan = OneMeasureTuner([N], budget_s=600, kinds=("ntt",)).run()
    assert plan.cell("ntt", N) is None


def test_tiny_calibration_fresh_then_store(tmp_path, monkeypatch):
    """Real measure pass (ntt + field at 2^6 on XLA:CPU) through
    load_or_run: first start calibrates + persists, the second adopts
    the stored plan with ZERO measurement runs (Autotuner poisoned)."""
    store = ArtifactStore(str(tmp_path))
    m = Metrics()
    real = AT.Autotuner

    def small_tuner(shapes, budget_s=None, metrics=None, **kw):
        return real(shapes, budget_s=budget_s, metrics=metrics,
                    kinds=("ntt", "field"), **kw)

    monkeypatch.setattr(AT, "Autotuner", small_tuner)
    rep = calibration.load_or_run(store, mode="run", shapes=[N],
                                  budget_s=600, metrics=m, aot=False)
    assert rep["source"] == "fresh" and rep["measure_runs"] > 0
    plan = AT.active_plan()
    assert plan is not None and plan.cell("ntt", N) is not None
    ntt_cell = plan.cell("ntt", N)
    assert ntt_cell["params"]["kernel"] == "xla"
    assert ntt_cell["params"]["radix"] in NJ.RADIX_CHOICES
    assert plan.cell("field", N)["params"]["mul"] in ("f32", "u32")
    assert m.snapshot()["counters"]["autotune_plan_stores"] == 1

    def poisoned(*a, **kw):
        raise AssertionError("second start must not measure")

    monkeypatch.setattr(AT, "Autotuner", poisoned)
    m2 = Metrics()
    rep2 = calibration.load_or_run(store, mode="run", shapes=[N],
                                   metrics=m2, aot=False)
    assert rep2["source"] == "store" and rep2["measure_runs"] == 0
    assert m2.snapshot()["counters"]["autotune_plan_loads"] == 1
    assert m2.snapshot()["counters"].get("autotune_measure_runs", 0) == 0
    assert AT.active_plan().to_json_bytes() == plan.to_json_bytes()
    # the winner's dispatch is bit-identical to the parity core
    v = _mont_vec(N)
    with_plan = np.asarray(
        NJ.get_plan(N).kernel(boundary="mont")(v)).tobytes()
    AT.set_active_plan(None)
    parity = np.asarray(NJ.get_plan(N).kernel(
        boundary="mont", radix=2, kernel="xla")(v)).tobytes()
    assert with_plan == parity


def test_msm_candidates_collapse_through_resolvers(monkeypatch):
    """Candidate dedup: an env-pinned dimension collapses the grid onto
    what would actually run, so pinned configs are measured once."""
    tuner = AT.Autotuner([N], budget_s=600)
    monkeypatch.setenv("DPT_MSM_GROUP_MAX", "512")
    sigs = {tuple(sorted(tuner._resolved("msm", N, c).items()))
            for c in tuner._candidates("msm", N)}
    assert all(dict(s)["group_max"] == 512 for s in sigs)
    assert len(sigs) == 2  # only the bucket_update axis survives on CPU


# --- memo invalidation across plan reloads -----------------------------------

def test_plan_reload_invalidates_kernel_memos():
    rev0 = AT.plan_revision()
    assert AT.cache_key("a", 1) == ("a", 1, rev0)
    plan = _plan_for_here({("ntt", N): {"params": {"radix": 2}}})
    AT.set_active_plan(plan)
    p = NJ.get_plan(N)
    p.kernel(boundary="mont")
    n_fns = len(p._fns)
    # same plan re-installed (a reload): same resolved config, but the
    # revision bump means the old compiled entry is never served
    AT.set_active_plan(plan)
    assert AT.plan_revision() > rev0
    p.kernel(boundary="mont")
    assert len(p._fns) == n_fns + 1
    # MsmContext chunk/calibration keys fold the revision in too
    ctx = MJ.MsmContext([(1, 2)] * 8)
    k1 = ctx._chunk_key(8, 4)
    c1 = ctx._calib_key()
    AT.set_active_plan(plan)
    assert ctx._chunk_key(8, 4) != k1 and ctx._calib_key() != c1


def test_plan_rate_seeds_chunk_sizing(monkeypatch):
    """A calibrated adds/s rate sizes MSM chunks from the FIRST call —
    but only when the context dispatches the kernel the plan measured
    (an explicit override to the other kernel must not size chunks from
    the wrong rate)."""
    n = 300  # >= 256: the wide signed pipeline with c_batch
    AT.set_active_plan(_plan_for_here({("msm", n): {"params": {
        "kernel": "xla", "adds_per_s": 1e9}}}))
    ctx = MJ.MsmContext([(1, 2)] * n)
    assert ctx._plan_rate() == 1e9
    # env-forced pallas while the plan's rate was measured under xla
    monkeypatch.setattr(MJ, "_MSM_KERNEL", "pallas")
    assert MJ.MsmContext([(1, 2)] * n)._plan_rate() is None


# --- service + fleet worker pickup -------------------------------------------

def test_service_picks_up_store_plan(tmp_path):
    from distributed_plonk_tpu.service import ProofService

    store_dir = str(tmp_path / "store")
    calibration.store_plan(
        ArtifactStore(store_dir),
        _plan_for_here({("ntt", N): {"params": {"radix": 2}}}))
    svc = ProofService(port=0, prover_workers=1,
                       store_dir=store_dir).start()
    try:
        assert svc.autotune["source"] == "store"
        assert svc.autotune["measure_runs"] == 0
        snap = svc.metrics.snapshot()
        assert snap["counters"]["autotune_plan_loads"] == 1
        assert snap["counters"].get("autotune_measure_runs", 0) == 0
        assert snap["gauges"]["autotune_plan_source"] == "store"
        assert AT.active_plan().fingerprint == AT.machine_fingerprint()
    finally:
        svc.shutdown()


def test_worker_picks_up_store_plan(tmp_path):
    import socket

    from distributed_plonk_tpu.runtime import native, protocol, worker
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig

    store_dir = str(tmp_path / "wstore")
    calibration.store_plan(
        ArtifactStore(store_dir),
        _plan_for_here({("field", N): {"params": {"mul": "u32"}}}))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ready = threading.Event()
    t = threading.Thread(
        target=worker.serve,
        args=(0, NetworkConfig([f"127.0.0.1:{port}"])),
        kwargs={"backend_name": "python", "ready_event": ready,
                "store_dir": store_dir},
        daemon=True)
    t.start()
    assert ready.wait(timeout=30)
    try:
        plan = AT.active_plan()
        assert plan is not None
        assert plan.lookup("field", "mul") == "u32"
    finally:
        conn = native.connect("127.0.0.1", port)
        conn.send(protocol.SHUTDOWN)
        assert conn.recv()[0] == protocol.OK
        conn.close()
        t.join(timeout=15)
