"""Device limb field kernels vs the pure-Python oracle (fields.py).

Everything runs under jit: this JAX build has very high per-op eager dispatch
overhead, and jit is the only mode the framework ever uses on device anyway.
"""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_plonk_tpu.constants import R_MOD, Q_MOD
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend.limbs import ints_to_limbs, limbs_to_ints

RNG = random.Random(0xF1E1D)


def _rand_elems(mod, n):
    vals = [RNG.randrange(mod) for _ in range(n - 3)]
    return vals + [0, 1, mod - 1]


@pytest.mark.parametrize("spec,mod", [(FJ.FR, R_MOD), (FJ.FQ, Q_MOD)])
def test_add_sub_neg(spec, mod):
    n = 64
    a_int = _rand_elems(mod, n)
    b_int = _rand_elems(mod, n)
    a = jnp.asarray(ints_to_limbs(a_int, spec.n_limbs))
    b = jnp.asarray(ints_to_limbs(b_int, spec.n_limbs))

    @jax.jit
    def f(a, b):
        return FJ.add(spec, a, b), FJ.sub(spec, a, b), FJ.neg(spec, a)

    s, d, ng = f(a, b)
    assert limbs_to_ints(s) == [(x + y) % mod for x, y in zip(a_int, b_int)]
    assert limbs_to_ints(d) == [(x - y) % mod for x, y in zip(a_int, b_int)]
    assert limbs_to_ints(ng) == [(-x) % mod for x in a_int]


@pytest.mark.parametrize("spec,mod", [(FJ.FR, R_MOD), (FJ.FQ, Q_MOD)])
def test_mont_mul_roundtrip(spec, mod):
    n = 64
    a_int = _rand_elems(mod, n)
    b_int = _rand_elems(mod, n)
    a = jnp.asarray(ints_to_limbs(a_int, spec.n_limbs))
    b = jnp.asarray(ints_to_limbs(b_int, spec.n_limbs))

    @jax.jit
    def f(a, b):
        am = FJ.to_mont(spec, a)
        bm = FJ.to_mont(spec, b)
        return FJ.from_mont(spec, FJ.mont_mul(spec, am, bm)), FJ.from_mont(spec, am)

    prod, rt = f(a, b)
    assert limbs_to_ints(prod) == [x * y % mod for x, y in zip(a_int, b_int)]
    assert limbs_to_ints(rt) == a_int  # to_mont/from_mont round-trips


def test_mont_repr_matches_arkworks_radix():
    """Montgomery form is x * 2^(16L) mod p — arkworks' radix, so device
    Montgomery values are bit-compatible with the reference's in-memory form."""
    xs = [1, 2, R_MOD - 1]
    a = jax.jit(lambda x: FJ.to_mont(FJ.FR, x))(
        jnp.asarray(ints_to_limbs(xs, FJ.FR.n_limbs)))
    assert limbs_to_ints(a) == [x * (1 << 256) % R_MOD for x in xs]


@pytest.mark.parametrize("spec,mod", [(FJ.FR, R_MOD), (FJ.FQ, Q_MOD)])
def test_mul_chain_stays_reduced(spec, mod):
    """Long dependent chains never leave [0, p)."""
    n = 8
    rounds = 6
    a_int = _rand_elems(mod, n)

    @jax.jit
    def f(x):
        xm = FJ.to_mont(spec, x)
        acc = xm
        for _ in range(rounds):
            acc = FJ.mont_mul(spec, acc, xm)
            acc = FJ.add(spec, acc, xm)
        return FJ.from_mont(spec, acc)

    expect = list(a_int)
    for _ in range(rounds):
        expect = [(e * v + v) % mod for e, v in zip(expect, a_int)]
    got = f(jnp.asarray(ints_to_limbs(a_int, spec.n_limbs)))
    assert limbs_to_ints(got) == expect


def test_predicates_and_select():
    xs = [0, 5, R_MOD - 1, 0]
    a = jnp.asarray(ints_to_limbs(xs, FJ.FR.n_limbs))
    b = jnp.asarray(ints_to_limbs([0, 5, 7, 1], FJ.FR.n_limbs))

    @jax.jit
    def f(a, b):
        cond = jnp.asarray([True, False, True, False])
        return FJ.is_zero(FJ.FR, a), FJ.eq(FJ.FR, a, b), FJ.select(cond, a, b)

    z, e, sel = f(a, b)
    assert list(np.asarray(z)) == [True, False, False, True]
    assert list(np.asarray(e)) == [True, True, False, False]
    assert limbs_to_ints(sel) == [0, 5, R_MOD - 1, 1]


def test_mul_columns_f32_matches_u32_at_extremes():
    """The f32 byte-product path (VPU float products + MXU constant
    Toeplitz matmuls) must agree with the u32 reference path bit-for-bit,
    including at all-0xFFFF limbs where the exactness bounds
    (products <= 255^2, column sums < 2^23) are tight."""
    for l in (FJ.FR.n_limbs, FJ.FQ.n_limbs):
        cases = [
            np.full((l, 4), 0xFFFF, dtype=np.uint32),
            np.zeros((l, 4), dtype=np.uint32),
            np.asarray(ints_to_limbs(
                [RNG.randrange(1 << (16 * l)) for _ in range(4)], l)),
        ]
        for a_np in cases:
            for b_np in cases:
                a, b = jnp.asarray(a_np), jnp.asarray(b_np)
                got = jax.jit(
                    lambda a, b: FJ._mul_columns_f32(a, b, 2 * l))(a, b)
                ref = jax.jit(
                    lambda a, b: FJ._mul_columns_u32(a, b, 2 * l))(a, b)
                # column sums differ in representation (f32 path carries
                # bytes, u32 path carries 16-bit limbs) but the VALUE
                # (sum of col[k] * 2^16k) must match exactly, per element
                for j in range(a_np.shape[1]):
                    gv = sum(int(col[j]) << (16 * k)
                             for k, col in enumerate(np.asarray(got)))
                    rv = sum(int(col[j]) << (16 * k)
                             for k, col in enumerate(np.asarray(ref)))
                    assert gv == rv, (l, j)


def test_mont_mul_extreme_operands():
    """mont_mul at the largest reduced operands (p-1) in both fields."""
    for spec, mod in ((FJ.FR, R_MOD), (FJ.FQ, Q_MOD)):
        xs = [mod - 1, mod - 1, 1, mod - 2]
        ys = [mod - 1, 1, mod - 1, mod - 2]
        a = jnp.asarray(ints_to_limbs(xs, spec.n_limbs))
        b = jnp.asarray(ints_to_limbs(ys, spec.n_limbs))

        @jax.jit
        def f(a, b):
            return FJ.from_mont(
                spec, FJ.mont_mul(spec, FJ.to_mont(spec, a),
                                  FJ.to_mont(spec, b)))

        assert limbs_to_ints(f(a, b)) == [x * y % mod for x, y in zip(xs, ys)]
