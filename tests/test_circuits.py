"""Circuit zoo tests (ISSUE 17): every kind builds a satisfiable circuit
whose STRUCTURE (gates, wiring, selectors) is a pure function of params —
the contract that lets a shape bucket's SRS + proving key be shared — and
proves/verifies byte-deterministically through the service's spec path.
"""

import random

import pytest

from distributed_plonk_tpu import circuits
from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit, shape_key)
from distributed_plonk_tpu.verifier import verify

# the smallest interesting member of each family (rollup is the big one:
# its height-1/1-update instance already finalizes at n=1024)
ZOO = [
    ("range", {"bits": 8, "count": 2}),
    ("preimage", {"count": 1}),
    ("rollup", {"height": 1, "updates": 1, "num_accounts": 2}),
]


def test_registry_covers_the_zoo():
    assert circuits.KINDS == ("preimage", "range", "rollup")
    with pytest.raises(ValueError):
        circuits.validate_params("nope", {})
    with pytest.raises(ValueError):
        circuits.build("nope", {}, 0)


@pytest.mark.parametrize("kind,params", ZOO, ids=[k for k, _ in ZOO])
def test_builds_finalized_and_power_of_two(kind, params):
    ckt = circuits.build(kind, params, seed=7)
    assert ckt.n == len(ckt.wire_variables[0])
    assert ckt.n >= 2 and ckt.n & (ckt.n - 1) == 0  # power of two
    assert ckt.public_input()  # every zoo circuit states something public


@pytest.mark.parametrize("kind,params", ZOO, ids=[k for k, _ in ZOO])
def test_structure_from_params_not_seed(kind, params):
    """Same params, different seeds -> identical gates/wiring/selectors;
    only witness values (and so public inputs) may differ."""
    a = circuits.build(kind, params, seed=7)
    b = circuits.build(kind, params, seed=8)
    assert a.wire_variables == b.wire_variables
    assert a.selectors == b.selectors
    assert a.pub_input_gate_ids == b.pub_input_gate_ids
    assert a.witness != b.witness  # the seed must matter somewhere


@pytest.mark.parametrize("bad", [
    {"kind": "range", "bits": 0, "seed": 1},
    {"kind": "range", "bits": 65, "seed": 1},
    {"kind": "range", "bits": 8, "count": 0, "seed": 1},
    {"kind": "preimage", "count": 0, "seed": 1},
    {"kind": "preimage", "count": 10**6, "seed": 1},
    {"kind": "rollup", "height": 0, "seed": 1},
    {"kind": "rollup", "height": 1, "updates": 0, "seed": 1},
    {"kind": "rollup", "height": 1, "num_accounts": 99, "seed": 1},
])
def test_bad_specs_rejected(bad):
    with pytest.raises(ValueError):
        JobSpec.from_wire(bad)


@pytest.mark.parametrize("wire", [
    {"kind": "range", "bits": 8, "count": 2, "seed": 3},
    {"kind": "preimage", "count": 1, "seed": 3},
], ids=["range", "preimage"])
def test_prove_verify_byte_deterministic(wire):
    """The cheap kinds prove through the service spec path: two same-seed
    runs produce byte-identical proofs, and they verify."""
    spec = JobSpec.from_wire(wire)
    _, pk, vk = build_bucket_keys(spec)[:3]
    proofs = []
    for _ in range(2):
        ckt = build_circuit(spec)
        proofs.append((serialize_proof(
            prove(random.Random(spec.seed), ckt, pk, PythonBackend())),
            ckt.public_input()))
    assert proofs[0] == proofs[1]
    blob, pub = proofs[0]
    from distributed_plonk_tpu.proof_io import deserialize_proof
    assert verify(vk, pub, deserialize_proof(blob), rng=random.Random(1))


def test_shape_key_distinguishes_kinds_at_same_domain_size():
    """toy gates=16 and range bits=8/count=2 both finalize at n=32; the
    bucket key must still keep them apart (kind is part of the key), or
    one kind's proving key would silently prove the other's circuits."""
    toy = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 1})
    rng_ = JobSpec.from_wire({"kind": "range", "bits": 8, "count": 2,
                              "seed": 1})
    assert build_circuit(toy).n == build_circuit(rng_).n == 32
    assert shape_key(toy) != shape_key(rng_)


def test_rollup_state_transition_roots_differ():
    """The rollup circuit's public inputs are (root_before, root_after);
    a batch that moves balances must move the root."""
    ckt = circuits.build("rollup",
                         {"height": 1, "updates": 1, "num_accounts": 2},
                         seed=11)
    pub = ckt.public_input()
    assert len(pub) == 2 and pub[0] != pub[1]
