"""Curve + pairing oracle tests."""

import random

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.fields import fq12_pow, FQ12_ONE

rng = random.Random(0xC1C1E)


def _msb_mul_affine(p, k):
    t = None
    for b in bin(k)[2:]:
        t = C.g1_add_affine(t, t) if t is not None else None
        if b == "1":
            t = C.g1_add_affine(t, p)
    return t


def test_generators_on_curve_and_order():
    assert C.g1_is_on_curve(C.G1_GEN)
    assert C.g2_is_on_curve(C.G2_GEN)
    # unreduced scalar: r * G == O (g1_mul reduces mod r, so do it manually)
    assert _msb_mul_affine(C.G1_GEN, R_MOD) is None
    assert C.g2_mul(C.G2_GEN, R_MOD - 1) == C.g2_neg(C.G2_GEN)


def test_g1_jacobian_vs_affine():
    p = C.G1_GEN
    for k in [2, 3, 5, 17, 12345, rng.randrange(1 << 64)]:
        assert C.g1_mul(p, k) == _msb_mul_affine(p, k)


def test_g1_add_edge_cases():
    p = C.G1_GEN
    assert C.g1_add_affine(p, None) == p
    assert C.g1_add_affine(None, p) == p
    assert C.g1_add_affine(p, C.g1_neg(p)) is None
    assert C.g1_add_affine(p, p) == C.g1_mul(p, 2)
    j = C.g1_jac_add(C.g1_to_jac(p), (1, 1, 0))
    assert C.g1_from_jac(j) == p


def test_msm_oracle_matches_naive():
    n = 16
    pts = [C.g1_mul(C.G1_GEN, rng.randrange(R_MOD)) for _ in range(n)]
    pts[3] = None  # infinity padding, as the reference's SRS zero-pad
    scalars = [rng.randrange(R_MOD) for _ in range(n)]
    scalars[5] = 0
    naive = None
    for p, s in zip(pts, scalars):
        if p is not None:
            naive = C.g1_add_affine(naive, C.g1_mul(p, s))
    assert C.g1_msm(pts, scalars) == naive


def test_pairing_bilinear():
    a, b = 1234567, 7654321
    e = C.pairing(C.G1_GEN, C.G2_GEN)
    assert e != FQ12_ONE
    assert C.pairing(C.g1_mul(C.G1_GEN, a), C.g2_mul(C.G2_GEN, b)) == fq12_pow(e, a * b % R_MOD)


def test_pairing_check():
    k = 424242
    good = [
        (C.g1_mul(C.G1_GEN, k), C.G2_GEN),
        (C.g1_neg(C.G1_GEN), C.g2_mul(C.G2_GEN, k)),
    ]
    assert C.pairing_check(good)
    bad = [
        (C.g1_mul(C.G1_GEN, k), C.G2_GEN),
        (C.g1_neg(C.G1_GEN), C.g2_mul(C.G2_GEN, k + 1)),
    ]
    assert not C.pairing_check(bad)
