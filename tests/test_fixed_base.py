"""Device fixed-base batch scalar mul + device SRS/preprocess path.

Oracle: the host double-and-add walk the reference's jf-plonk setup does
(/root/reference/src/dispatcher2.rs:1279). Invariant: DeviceSrs powers and
DeviceCommitKey commitments are bit-identical to the host oracle's."""

import random

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import kzg
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import curve_jax as CJ
from distributed_plonk_tpu.backend.fixed_base import FixedBaseContext


def test_batch_mul_matches_host_oracle():
    rng = random.Random(3)
    # edge scalars: 0 -> infinity, 1 -> G, r-1 -> -G, plus randoms
    scalars = [0, 1, R_MOD - 1, 2] + [rng.randrange(R_MOD) for _ in range(12)]
    ctx = FixedBaseContext(C.G1_GEN)
    got = CJ.device_to_affine(ctx.batch_mul(scalars))
    want = [C.g1_mul(C.G1_GEN, s) for s in scalars]
    assert got == want


def test_device_srs_matches_host_setup():
    srs_h = kzg.universal_setup(33, tau=987654321)
    srs_d = kzg.universal_setup_device(33, tau=987654321)
    assert srs_d.count == 34
    assert srs_d.powers_affine() == srs_h.powers_of_g1
    assert srs_d.tau_g2 == srs_h.tau_g2


def test_device_preprocess_matches_host(proven_inputs):
    """Device SRS + backend preprocess produce the identical pk/vk (and so
    the identical transcript/proof downstream) as the host-oracle path."""
    from distributed_plonk_tpu.backend.jax_backend import JaxBackend

    ckt, srs_h, pk_h, vk_h = proven_inputs
    srs_d = kzg.universal_setup_device(ckt.n + 2, tau=424242)
    be = JaxBackend()
    pk_d, vk_d = kzg.preprocess(srs_d, ckt, backend=be)
    assert vk_d.selector_comms == vk_h.selector_comms
    assert vk_d.sigma_comms == vk_h.sigma_comms
    assert pk_d.selectors == pk_h.selectors
    assert pk_d.sigmas == pk_h.sigmas


@pytest.fixture(scope="module")
def proven_inputs():
    from distributed_plonk_tpu.workload import generate_circuit

    ckt, _ = generate_circuit(rng=random.Random(5), height=2, num_proofs=1)
    srs = kzg.universal_setup(ckt.n + 2, tau=424242)
    pk, vk = kzg.preprocess(srs, ckt)
    return ckt, srs, pk, vk
