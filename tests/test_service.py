"""Proof service tests: queue admission, bucket reuse, TCP round trip,
and checkpoint-resume retry after a deterministic worker kill.

Everything runs in-process on the host oracle backend at tiny domains
(n=16..32) so the whole module stays in the fast tier; the wire tests go
through real TCP via the native framed transport.
"""

import random
import threading

import pytest

from distributed_plonk_tpu.service import (ProofService, ServiceClient,
                                           JobQueue, Rejected)
from distributed_plonk_tpu.service.jobs import (Job, JobSpec, build_circuit,
                                                build_bucket_keys)
from distributed_plonk_tpu.service.client import ServiceError
from distributed_plonk_tpu.proof_io import deserialize_proof, serialize_proof
from distributed_plonk_tpu.verifier import verify

TOY_A = {"kind": "toy", "gates": 8}
TOY_B = {"kind": "toy", "gates": 12}


def _job(spec_dict, seed=0, priority=0):
    d = dict(spec_dict)
    d.update(seed=seed, priority=priority)
    return Job(JobSpec.from_wire(d))


# --- queue -------------------------------------------------------------------

def test_queue_admission_and_backpressure():
    q = JobQueue(max_depth=2)
    q.submit(_job(TOY_A))
    q.submit(_job(TOY_A))
    with pytest.raises(Rejected, match="queue_full"):
        q.submit(_job(TOY_A))
    assert q.depth() == 2 and q.high_water == 2
    q.close()
    with pytest.raises(Rejected, match="draining"):
        q.submit(_job(TOY_A))


def test_queue_priority_and_shape_batching():
    q = JobQueue(max_depth=16)
    low = _job(TOY_A, seed=1, priority=0)
    high_b = _job(TOY_B, seed=2, priority=5)
    high_b2 = _job(TOY_B, seed=3, priority=1)
    low_b = _job(TOY_B, seed=4, priority=0)
    for j in (low, high_b, high_b2, low_b):
        q.submit(j)
    # best job is high_b; the batch is every TOY_B job, priority order
    batch = q.pop_batch(max_batch=8, timeout=0)
    assert [j.id for j in batch] == [high_b.id, high_b2.id, low_b.id]
    assert q.pop_batch(max_batch=8, timeout=0) == [low]
    assert q.pop_batch(max_batch=8, timeout=0) == []


def test_queue_batch_cap():
    q = JobQueue(max_depth=16)
    a_jobs = [_job(TOY_A, seed=i) for i in range(4)]
    for j in a_jobs:
        q.submit(j)
    batch = q.pop_batch(max_batch=3, timeout=0)
    assert [j.id for j in batch] == [j.id for j in a_jobs[:3]]
    assert q.depth() == 1


# --- spec validation ---------------------------------------------------------

def test_spec_validation():
    with pytest.raises(ValueError, match="unknown kind"):
        JobSpec.from_wire({"kind": "nope"})
    with pytest.raises(ValueError, match="gates"):
        JobSpec.from_wire({"kind": "toy", "gates": 0})
    with pytest.raises(ValueError, match="JSON object"):
        JobSpec.from_wire([1, 2])
    spec = JobSpec.from_wire({"kind": "merkle", "height": 2, "seed": 9})
    assert spec.params == {"height": 2, "num_proofs": 1, "num_leaves": 3}


# --- full service over TCP ---------------------------------------------------

@pytest.fixture()
def service():
    svc = ProofService(port=0, prover_workers=2, chaos=True).start()
    yield svc
    svc.shutdown()


def _verify_wire_result(header, blob):
    spec = JobSpec.from_wire(header["spec"])
    vk = build_bucket_keys(spec)[2]
    pub = [int(x, 16) for x in header["public_input"]]
    return verify(vk, pub, deserialize_proof(blob), rng=random.Random(1))


def test_tcp_round_trip_and_bucket_reuse(service):
    with ServiceClient("127.0.0.1", service.port) as c:
        c.ping()
        ids = [c.submit(dict(TOY_A, seed=s))["job_id"] for s in (1, 2)]
        ids.append(c.submit(dict(TOY_B, seed=3))["job_id"])
        for jid in ids:
            st = c.wait(jid, timeout_s=180)
            assert st["state"] == "done", st
            header, blob = c.result(jid)
            assert header["job_id"] == jid
            assert _verify_wire_result(header, blob)
        m = c.metrics()
    # two shapes -> exactly two key builds, the same-shape job reused one
    assert m["counters"]["bucket_misses"] == 2
    assert m["counters"]["bucket_hits"] >= 1
    assert m["counters"]["jobs_completed"] == 3
    assert "queue_depth" in m["gauges"]
    assert m["histograms"]["job_wait"]["count"] == 3
    assert m["histograms"]["prove_round/round1"]["count"] >= 3


def test_warmup_over_wire(tmp_path):
    svc = ProofService(port=0, prover_workers=1,
                       store_dir=str(tmp_path / "store")).start()
    try:
        with ServiceClient("127.0.0.1", svc.port) as c:
            w1 = c.warmup(TOY_A)
            assert w1["source"] == "built" and w1["build_s"] > 0
            w2 = c.warmup(TOY_A)
            assert w2["source"] == "memory"
            # aot on the host-oracle pool backend: reported, not an error
            assert c.warmup(TOY_A, aot=True)["aot"]["aot"] == "unsupported"
            with pytest.raises(ServiceError, match="bad_spec"):
                c.warmup({"kind": "toy", "gates": 0})
            # a submit for the warmed shape never builds keys
            jid = c.submit(dict(TOY_A, seed=4))["job_id"]
            assert c.wait(jid, timeout_s=180)["state"] == "done"
            m = c.metrics()
        assert m["counters"]["warmups"] == 3
        assert m["counters"]["bucket_misses"] == 1   # the warmup's build
        assert m["counters"]["bucket_hits"] >= 3
        assert m["counters"]["store_put_bytes"] > 0  # keys persisted
    finally:
        svc.shutdown()

    # restarted service over the same store: WARMUP reports a disk hit
    svc2 = ProofService(port=0, prover_workers=1,
                        store_dir=str(tmp_path / "store")).start()
    try:
        with ServiceClient("127.0.0.1", svc2.port) as c:
            assert c.warmup(TOY_A)["source"] == "disk"
        assert svc2.metrics.snapshot()["counters"]["bucket_disk_hits"] == 1
    finally:
        svc2.shutdown()


def test_tcp_errors(service):
    with ServiceClient("127.0.0.1", service.port) as c:
        with pytest.raises(ServiceError, match="bad_spec"):
            c.submit({"kind": "toy", "gates": -1})
        with pytest.raises(ServiceError, match="unknown job"):
            c.status("job-999999")
        jid = c.submit(dict(TOY_A, seed=7))["job_id"]
        # RESULT before completion is a clean not_ready, then real bytes
        try:
            c.result(jid)
        except ServiceError as e:
            assert e.info["reason"] == "not_ready"
        c.wait(jid, timeout_s=180)
        header, blob = c.result(jid)
        assert len(blob) == 944


def test_queue_full_over_wire():
    svc = ProofService(port=0, prover_workers=1, queue_depth=1).start()
    try:
        # stall the scheduler's only consumer path by filling depth-1 queue
        # faster than the single worker drains it
        with ServiceClient("127.0.0.1", svc.port) as c:
            seen_full = False
            ids = []
            for s in range(12):
                try:
                    ids.append(c.submit(dict(TOY_A, seed=100 + s))["job_id"])
                except ServiceError as e:
                    assert e.info["reason"] == "queue_full"
                    assert "max_depth" in e.info
                    seen_full = True
            assert seen_full, "depth-1 queue never pushed back on a burst"
            for jid in ids:
                assert c.wait(jid, timeout_s=300)["state"] == "done"
    finally:
        svc.shutdown()


# --- kill / checkpoint-resume retry -----------------------------------------

def test_killed_worker_resumes_from_checkpoint():
    svc = ProofService(port=0, prover_workers=1, chaos=True).start()
    try:
        # arm the kill BEFORE the job runs: the single worker dies right
        # after persisting round 2, deterministically
        victim = svc.pool.kill_worker(worker="w0g1", at_round=2)
        assert victim == "w0g1"
        job = svc.submit_local(dict(TOY_B, seed=11, priority=0))
        assert job.done_event.wait(timeout=240)
        assert job.state == "done"
        assert job.retries == 1
        assert [a["outcome"] for a in job.attempts] == ["killed", "ok"]
        assert job.attempts[0]["worker"] == "w0g1"
        assert job.attempts[1]["worker"] == "w0g2"  # respawned slot

        # resume must be byte-identical to an uninterrupted prove of the
        # same spec against the same bucket keys
        spec = JobSpec.from_wire(dict(TOY_B, seed=11))
        _, pk, vk = build_bucket_keys(spec)
        ckt = build_circuit(spec)
        from distributed_plonk_tpu.backend.python_backend import PythonBackend
        from distributed_plonk_tpu.prover import prove
        want = serialize_proof(prove(random.Random(11), ckt, pk,
                                     PythonBackend()))
        assert job.proof_bytes == want
        assert verify(vk, job.public_input,
                      deserialize_proof(job.proof_bytes),
                      rng=random.Random(2))
        m = svc.metrics.snapshot()
        assert m["counters"]["workers_killed"] == 1
        assert m["counters"]["job_retries"] == 1
        assert m["counters"]["workers_spawned"] == 2
    finally:
        svc.shutdown()


def test_job_timeout_fails_cleanly():
    svc = ProofService(port=0, prover_workers=1, job_timeout_s=0.0001).start()
    try:
        job = svc.submit_local(dict(TOY_A, seed=5))
        assert job.done_event.wait(timeout=240)
        assert job.state == "failed"
        assert "timeout" in job.error
        m = svc.metrics.snapshot()
        assert m["counters"]["jobs_timeout"] == 1
        assert m["counters"]["jobs_failed"] == 1
    finally:
        svc.shutdown()
