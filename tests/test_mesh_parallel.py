"""Sharded NTT + MSM on the 8-device virtual CPU mesh vs the host oracles.

The mesh analog of the reference's distributed integration tests
(`test_fft` /root/reference/src/dispatcher.rs:246-350 — all 8 flag combos
against ark-poly — and `test_msm` src/dispatcher.rs:177-244), but run on an
in-process device mesh instead of a live 2-host cluster (SURVEY.md §4's
"missing piece" the rebuild adds).
"""

import random

import jax
import pytest

from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.parallel.mesh import make_mesh
from distributed_plonk_tpu.parallel.ntt_mesh import MeshNttPlan
from distributed_plonk_tpu.parallel.msm_mesh import MeshMsmContext

RNG = random.Random(0x8E5)


def _oracle(domain, values, inverse, coset):
    if inverse and coset:
        return P.coset_ifft(domain, values)
    if inverse:
        return P.ifft(domain, values)
    if coset:
        return P.coset_fft(domain, values)
    return P.fft(domain, values)


@pytest.fixture(scope="module")
def mesh8():
    # explicit cpu: the axon TPU plugin outranks JAX_PLATFORMS on this host
    return make_mesh(8, platform="cpu")


@pytest.fixture(scope="module")
def plan256(mesh8):
    return MeshNttPlan(mesh8, 256)


@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("coset", [False, True])
def test_mesh_ntt_matches_oracle(plan256, inverse, coset):
    n = plan256.n
    domain = P.Domain(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    got = plan256.run_ints(values, inverse=inverse, coset=coset)
    assert got == _oracle(domain, values, inverse, coset)


def test_mesh_ntt_radix2_core_parity(mesh8, plan256, monkeypatch):
    """The mesh 4-step NTT runs its row/column butterflies through the
    SHARED stage core (ntt_jax.run_stages): flipping DPT_NTT_RADIX=2
    must reproduce the default radix-4 mesh result bit for bit."""
    values = [RNG.randrange(R_MOD) for _ in range(plan256.n)]
    want = plan256.run_ints(values)
    monkeypatch.setenv("DPT_NTT_RADIX", "2")
    got = plan256.run_ints(values)
    assert got == want
    from distributed_plonk_tpu.backend import autotune
    assert autotune.cache_key(False, False, "plain", 2, "xla") \
        in plan256._fns
    assert autotune.cache_key(False, False, "plain", 4, "xla") \
        in plan256._fns


def test_mesh_ntt_roundtrip_uneven_rc(mesh8):
    # n = 512: r = 16, c = 32 (r != c exercises the all_to_all shapes)
    plan = MeshNttPlan(mesh8, 512)
    values = [RNG.randrange(R_MOD) for _ in range(512)]
    domain = P.Domain(512)
    assert plan.run_ints(values) == P.fft(domain, values)
    assert plan.run_ints(plan.run_ints(values), inverse=True) == values


def test_mesh_commit_paths_never_dispatch_pallas(mesh8, monkeypatch):
    """ADVICE r4 regression: _digits_of_handles and _merge_fn trace
    mont_mul on GSPMD-sharded/replicated operands OUTSIDE shard_map,
    where a pallas_call (no SPMD partitioning rule) breaks on a real TPU
    mesh. Force the pallas dispatch mode at any width and assert those
    jits never reach the pallas kernel — while still extracting correct
    digits."""
    import numpy as np
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend import field_jax as FJ
    from distributed_plonk_tpu.backend import field_pallas as FP
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs
    from distributed_plonk_tpu.constants import FR_MONT_R

    monkeypatch.setattr(FJ, "_MUL_MODE", "pallas")
    monkeypatch.setattr(FJ, "_PALLAS_MIN_LANES", 1)
    hits = []
    real_mul = FP.mont_mul

    def spy(spec, a, b):
        hits.append(a.shape)
        return real_mul(spec, a, b)

    monkeypatch.setattr(FP, "mont_mul", spy)

    n = 64
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(8)]
    ctx = MeshMsmContext(mesh8, [pts[i % 8] for i in range(n)])
    scalars = [RNG.randrange(R_MOD) for _ in range(n)]
    h = jnp.asarray(ints_to_limbs([s * FR_MONT_R % R_MOD for s in scalars], 16))
    digits = ctx._digits_of_handles([h])
    assert not hits, f"pallas dispatched in sharded digit extraction: {hits}"
    assert np.array_equal(np.asarray(digits)[0], ctx._digits_np(scalars))

    planes = tuple(jnp.ones((24, 8, 16), jnp.uint32) for _ in range(3))
    jax.block_until_ready(ctx._merge_fn(planes, planes))
    assert not hits, f"pallas dispatched in the cross-chunk merge: {hits}"


@pytest.mark.slow
def test_mesh_msm_pallas_kernel_parity(mesh8, monkeypatch):
    """The per-shard bucket scans inside the mesh MSM pick up
    DPT_MSM_KERNEL=pallas unchanged (shard_map bodies see per-device
    local shapes, where a pallas_call is legal), and the folded result
    matches the XLA-kernel mesh run. On the CPU test mesh pallas_guard
    would veto the kernel (it exists to keep Mosaic off non-TPU
    meshes), so the guard is opened and the kernel runs interpret-mode
    — the same dispatch seam a TPU mesh exercises compiled."""
    import contextlib
    from distributed_plonk_tpu.backend import msm_jax as MJ
    from distributed_plonk_tpu.parallel import msm_mesh as MM

    n = 32
    bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n)]
    scalars = [RNG.randrange(R_MOD) for _ in range(n)]
    want = MeshMsmContext(mesh8, bases).msm(scalars)
    assert want == C.g1_msm(bases, scalars)
    monkeypatch.setattr(MJ, "_MSM_KERNEL", "pallas")
    monkeypatch.setattr(MM, "pallas_guard",
                        lambda mesh: contextlib.nullcontext())
    assert MeshMsmContext(mesh8, bases).msm(scalars) == want


def test_mesh_msm_matches_oracle(mesh8):
    n = 64
    bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n - 2)]
    bases += [None, None]
    scalars = ([RNG.randrange(R_MOD) for _ in range(n - 3)] + [0, 1, R_MOD - 1])
    ctx = MeshMsmContext(mesh8, bases)
    assert ctx.msm(scalars) == C.g1_msm(bases, scalars)
    # short scalar vector (zero-padded on device)
    short = [RNG.randrange(R_MOD) for _ in range(40)]
    assert ctx.msm(short) == C.g1_msm(bases[:40], short)
