// Native host data-plane + transport for distributed_plonk_tpu.
//
// Plays the role of the reference's native host components:
//   - zero-copy workload serialization (/root/reference/src/utils.rs:27-43)
//     -> here an explicit, layout-documented limb codec (no unsafe
//        transmutes: the wire format is defined, not accidental)
//   - CPU transpose kernels (/root/reference/src/transpose.rs)
//     -> blocked uint32 transpose for host-side panel reassembly
//   - Cap'n Proto two-party TCP RPC (/root/reference/src/worker.rs:441-536)
//     -> a minimal length-prefixed framed message transport (TCP_NODELAY),
//        enough to express the dispatcher<->worker control plane; bulk
//        data rides the same frames
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).
//
// Wire format: frame = [u64 payload_len (LE)][u32 tag (LE)][payload bytes].

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cerrno>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

extern "C" {

// --- limb codec --------------------------------------------------------------
// elements: n little-endian byte strings of elem_bytes each, concatenated.
// limbs: uint32 matrix, leading-limb layout (n_limbs, n) row-major, 16-bit
// limbs (the device layout, see distributed_plonk_tpu/backend/limbs.py).

void le_bytes_to_limbs(const uint8_t* in, uint64_t n, uint64_t elem_bytes,
                       uint32_t* out) {
    const uint64_t n_limbs = elem_bytes / 2;
    for (uint64_t i = 0; i < n; ++i) {
        const uint8_t* e = in + i * elem_bytes;
        for (uint64_t l = 0; l < n_limbs; ++l) {
            out[l * n + i] =
                (uint32_t)e[2 * l] | ((uint32_t)e[2 * l + 1] << 8);
        }
    }
}

// returns 0 on success, -1 if any limb value exceeds 16 bits (unreduced
// kernel output -- the same guard limbs.py applies at the oracle boundary)
int limbs_to_le_bytes(const uint32_t* in, uint64_t n, uint64_t elem_bytes,
                      uint8_t* out) {
    const uint64_t n_limbs = elem_bytes / 2;
    for (uint64_t l = 0; l < n_limbs; ++l) {
        const uint32_t* row = in + l * n;
        for (uint64_t i = 0; i < n; ++i) {
            uint32_t v = row[i];
            if (v > 0xFFFFu) return -1;
            out[i * elem_bytes + 2 * l] = (uint8_t)(v & 0xFF);
            out[i * elem_bytes + 2 * l + 1] = (uint8_t)(v >> 8);
        }
    }
    return 0;
}

// --- blocked transpose -------------------------------------------------------
// (rows, cols) -> (cols, rows), 64x64 tiles (cache-friendly; the reference's
// oop_transpose_medium plays this role, transpose.rs:110-198)

void transpose_u32(const uint32_t* in, uint64_t rows, uint64_t cols,
                   uint32_t* out) {
    const uint64_t T = 64;
    for (uint64_t r0 = 0; r0 < rows; r0 += T) {
        const uint64_t r1 = r0 + T < rows ? r0 + T : rows;
        for (uint64_t c0 = 0; c0 < cols; c0 += T) {
            const uint64_t c1 = c0 + T < cols ? c0 + T : cols;
            for (uint64_t r = r0; r < r1; ++r)
                for (uint64_t c = c0; c < c1; ++c)
                    out[c * rows + r] = in[r * cols + c];
        }
    }
}

// --- framed TCP transport ----------------------------------------------------

static int read_exact(int fd, uint8_t* buf, uint64_t len) {
    uint64_t got = 0;
    while (got < len) {
        ssize_t k = read(fd, buf + got, len - got);
        if (k <= 0) {
            if (k < 0 && errno == EINTR) continue;
            return -1;
        }
        got += (uint64_t)k;
    }
    return 0;
}

static int write_exact(int fd, const uint8_t* buf, uint64_t len) {
    uint64_t put = 0;
    while (put < len) {
        ssize_t k = write(fd, buf + put, len - put);
        if (k <= 0) {
            if (k < 0 && errno == EINTR) continue;
            return -1;
        }
        put += (uint64_t)k;
    }
    return 0;
}

// listener: returns listening fd or -1
int dpt_listen(const char* host, int port, int backlog) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) { close(fd); return -1; }
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) { close(fd); return -1; }
    if (listen(fd, backlog) != 0) { close(fd); return -1; }
    return fd;
}

int dpt_accept(int listen_fd) {
    int fd = accept(listen_fd, nullptr, nullptr);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return fd;
}

// timeout_ms <= 0: blocking connect (OS default, ~2 min on a dropped
// SYN). > 0: non-blocking connect + poll, so a partitioned/firewalled
// peer costs a bounded wait instead of stalling the caller (the store
// peer-fetch tier runs under the scheduler's bucket lock).
int dpt_connect(const char* host, int port, int timeout_ms) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) { close(fd); return -1; }
    if (timeout_ms <= 0) {
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) { close(fd); return -1; }
    } else {
        int flags = fcntl(fd, F_GETFL, 0);
        fcntl(fd, F_SETFL, flags | O_NONBLOCK);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            if (errno != EINPROGRESS) { close(fd); return -1; }
            pollfd p;
            p.fd = fd;
            p.events = POLLOUT;
            // retry on EINTR with the remaining budget: an interrupted
            // dial is not an unreachable peer (a spurious -1 here would
            // feed probe() a false death report)
            int remaining = timeout_ms;
            struct timeval tv0;
            gettimeofday(&tv0, nullptr);
            int rc;
            for (;;) {
                rc = poll(&p, 1, remaining);
                if (rc >= 0 || errno != EINTR) break;
                struct timeval tv1;
                gettimeofday(&tv1, nullptr);
                int elapsed = (int)((tv1.tv_sec - tv0.tv_sec) * 1000 +
                                    (tv1.tv_usec - tv0.tv_usec) / 1000);
                remaining = timeout_ms - elapsed;
                if (remaining <= 0) { rc = 0; break; }
            }
            if (rc <= 0) { close(fd); return -1; }
            int err = 0;
            socklen_t elen = sizeof(err);
            if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 ||
                err != 0) { close(fd); return -1; }
        }
        fcntl(fd, F_SETFL, flags);
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return fd;
}

// send one frame; returns 0 / -1
int dpt_send(int fd, uint32_t tag, const uint8_t* payload, uint64_t len) {
    uint8_t hdr[12];
    memcpy(hdr, &len, 8);
    memcpy(hdr + 8, &tag, 4);
    if (write_exact(fd, hdr, 12) != 0) return -1;
    if (len && write_exact(fd, payload, len) != 0) return -1;
    return 0;
}

// peek the next frame header; returns 0 and fills len/tag, or -1
int dpt_recv_header(int fd, uint64_t* len, uint32_t* tag) {
    uint8_t hdr[12];
    if (read_exact(fd, hdr, 12) != 0) return -1;
    memcpy(len, hdr, 8);
    memcpy(tag, hdr + 8, 4);
    return 0;
}

// read the payload announced by dpt_recv_header into caller buffer
int dpt_recv_payload(int fd, uint8_t* buf, uint64_t len) {
    return read_exact(fd, buf, len);
}

// receive/send timeout in milliseconds (0 = blocking forever); after a
// timeout fires mid-frame the stream is unsynchronized, so callers must
// treat it as fatal for the connection (reconnect) — returns 0 / -1
int dpt_set_timeout(int fd, int ms) {
    timeval tv;
    tv.tv_sec = ms / 1000;
    tv.tv_usec = (ms % 1000) * 1000;
    if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv)) != 0) return -1;
    if (setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv)) != 0) return -1;
    return 0;
}

int dpt_close(int fd) { return close(fd); }

}  // extern "C"
